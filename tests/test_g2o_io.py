"""g2o text-format ingestion: round-trip, conventions, SE(2) lift, solve.

The reference has no g2o file support (its only loader is the BAL text
parser, examples/BAL_Double.cpp:74-139) — this module covers the
capability-beyond-reference path that connects the PGO family to the
standard pose-graph dataset format.
"""

import dataclasses
import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
from megba_tpu.io.g2o import (
    G2OGraph,
    _info_g2o_to_ours,
    _info_ours_to_g2o,
    read_g2o,
    solve_g2o,
    sqrt_info_of,
    write_g2o,
)
from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo
from megba_tpu.ops import geo


def _option(max_iter=25):
    return ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-12,
                               epsilon2=1e-15),
        solver_option=SolverOption(max_iter=100, tol=1e-14,
                                   refuse_ratio=1e30),
    )


def _graph_of(g, info=None, fixed=None):
    n_e = len(g.edge_i)
    n = g.poses0.shape[0]
    if fixed is None:
        fixed = np.zeros(n, bool)
        fixed[0] = True
    return G2OGraph(
        poses=g.poses0, edge_i=g.edge_i, edge_j=g.edge_j, meas=g.meas,
        info=np.tile(np.eye(6), (n_e, 1, 1)) if info is None else info,
        fixed=fixed, ids=np.arange(n, dtype=np.int64))


def _rotmats(aa):
    return np.asarray(jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(aa)))


def test_roundtrip_exact_se3():
    g = make_synthetic_pose_graph(num_poses=12, loop_closures=3, seed=1)
    rng = np.random.default_rng(0)
    # Random SPD info per edge exercises the permutation + chart maps.
    a = rng.standard_normal((len(g.edge_i), 6, 6))
    info = a @ np.transpose(a, (0, 2, 1)) + 6 * np.eye(6)
    graph = _graph_of(g, info=info)
    graph.fixed[5] = True

    buf = io.StringIO()
    write_g2o(buf, graph)
    back = read_g2o(io.StringIO(buf.getvalue()))

    assert not back.se2
    np.testing.assert_array_equal(back.ids, graph.ids)
    np.testing.assert_array_equal(back.edge_i, graph.edge_i)
    np.testing.assert_array_equal(back.edge_j, graph.edge_j)
    np.testing.assert_array_equal(back.fixed, graph.fixed)
    # Rotations round-trip through the quaternion chart as SO(3)
    # elements; translations exactly (up to text precision).
    np.testing.assert_allclose(_rotmats(back.poses[:, :3]),
                               _rotmats(graph.poses[:, :3]), atol=1e-7)
    np.testing.assert_allclose(back.poses[:, 3:], graph.poses[:, 3:],
                               atol=1e-7)
    np.testing.assert_allclose(_rotmats(back.meas[:, :3]),
                               _rotmats(graph.meas[:, :3]), atol=1e-7)
    np.testing.assert_allclose(back.info, graph.info, rtol=1e-6,
                               atol=1e-6)


def test_info_permutation_involution():
    rng = np.random.default_rng(3)
    a = rng.standard_normal((6, 6))
    om = a @ a.T + 6 * np.eye(6)
    np.testing.assert_allclose(_info_g2o_to_ours(_info_ours_to_g2o(om)),
                               om, rtol=1e-12)
    # The chart factor: rotation block (ours rows 0-2) maps to the g2o
    # quaternion block (rows 3-5) scaled by 4, translation unscaled.
    ours = _info_g2o_to_ours(np.eye(6))
    np.testing.assert_allclose(np.diag(ours), [0.25] * 3 + [1.0] * 3)


def test_file_route_matches_direct_solve():
    g = make_synthetic_pose_graph(num_poses=14, loop_closures=4,
                                  drift_noise=0.05, seed=2)
    buf = io.StringIO()
    write_g2o(buf, _graph_of(g))
    graph, res = solve_g2o(io.StringIO(buf.getvalue()), _option())
    res_direct = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, _option())
    assert float(res.cost) < 1e-9 * max(float(res.initial_cost), 1.0)
    # Identity info -> sqrt_info_of returns None -> byte-identical path.
    assert sqrt_info_of(graph) is None
    np.testing.assert_allclose(float(res.cost), float(res_direct.cost),
                               rtol=1e-9, atol=1e-14)


def test_weighted_solve_and_psd_sqrt():
    g = make_synthetic_pose_graph(num_poses=10, loop_closures=2, seed=4)
    n_e = len(g.edge_i)
    info = np.tile(np.diag([4.0, 4.0, 4.0, 9.0, 9.0, 9.0]), (n_e, 1, 1))
    graph = _graph_of(g, info=info)
    w = sqrt_info_of(graph)
    assert w is not None
    np.testing.assert_allclose(
        np.einsum("eab,eac->ebc", w, w), info, rtol=1e-12)
    _, res = solve_g2o(graph, _option())
    assert float(res.cost) < 1e-9

    # Positive-SEMIdefinite info (an unconstrained DOF) must factor
    # cleanly, not crash.
    info_psd = np.tile(np.diag([1.0, 1.0, 1.0, 1.0, 1.0, 0.0]),
                       (n_e, 1, 1))
    w_psd = sqrt_info_of(_graph_of(g, info=info_psd))
    np.testing.assert_allclose(
        np.einsum("eab,eac->ebc", w_psd, w_psd), info_psd, atol=1e-12)

    # Indefinite info is a data error and must say which edge.
    info_bad = info.copy()
    info_bad[3] = np.diag([1.0, 1.0, 1.0, 1.0, 1.0, -2.0])
    with pytest.raises(ValueError, match="edge 3"):
        sqrt_info_of(_graph_of(g, info=info_bad))


def test_se2_lift_solves_planar():
    # A drifted square with one loop closure; all records SE2.
    text = """\
# planar graph
VERTEX_SE2 0 0 0 0
VERTEX_SE2 1 1.1 0.05 1.62
VERTEX_SE2 2 1.02 1.08 3.2
VERTEX_SE2 3 -0.07 0.93 -1.55
EDGE_SE2 0 1 1 0 1.5707963 1 0 0 1 0 1
EDGE_SE2 1 2 1 0 1.5707963 1 0 0 1 0 1
EDGE_SE2 2 3 1 0 1.5707963 1 0 0 1 0 1
EDGE_SE2 3 0 1 0 1.5707963 1 0 0 1 0 1
FIX 0
"""
    graph = read_g2o(io.StringIO(text))
    assert graph.se2
    assert graph.poses.shape == (4, 6)
    # Lifted info: unit weight on the out-of-plane rows.
    np.testing.assert_allclose(np.diag(graph.info[0]),
                               [1, 1, 1, 1, 1, 1], atol=1e-12)
    _, res = solve_g2o(graph, _option())
    assert float(res.cost) < 1e-12
    poses = np.asarray(res.poses)
    # Solution stays planar: no z translation, no in-plane rotation axes.
    assert float(np.abs(poses[:, [0, 1, 5]]).max()) < 1e-8
    # The four poses close a unit square.
    np.testing.assert_allclose(poses[2, 3:5], [1.0, 1.0], atol=1e-6)


def test_malformed_lines_raise_with_line_numbers():
    with pytest.raises(ValueError, match="line 1: VERTEX_SE3:QUAT"):
        read_g2o(io.StringIO("VERTEX_SE3:QUAT 5 1.0 2.0\n"))
    with pytest.raises(ValueError, match="line 2: EDGE_SE3:QUAT"):
        read_g2o(io.StringIO(
            "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
            "EDGE_SE3:QUAT 0 0 1 2 3\n"))
    with pytest.raises(ValueError, match="unknown vertex"):
        read_g2o(io.StringIO(
            "VERTEX_SE2 0 0 0 0\n"
            "EDGE_SE2 0 7 1 0 0 1 0 0 1 0 1\n"))
    with pytest.raises(ValueError, match="no supported VERTEX"):
        read_g2o(io.StringIO("# empty\nUNKNOWN_TAG 1 2 3\n"))


def test_unknown_tags_skipped_and_default_anchor():
    text = """\
VERTEX_TRACKXYZ 99 1 2 3
VERTEX_SE2 4 0 0 0
VERTEX_SE2 7 1 0 0
EDGE_SE2 4 7 1 0 0 1 0 0 1 0 1
"""
    graph = read_g2o(io.StringIO(text))
    np.testing.assert_array_equal(graph.ids, [4, 7])
    # No FIX line -> lowest-id vertex anchors the gauge.
    np.testing.assert_array_equal(graph.fixed, [True, False])


def test_negative_w_quaternions_fold_to_principal_branch():
    """q and -q are the same rotation; exporters emit either sign.

    The parser must fold w < 0 inputs onto the principal angle-axis
    branch [0, pi] exactly like ops/geo.quaternion_to_angle_axis
    (negating produces norm in (pi, 2pi] and a discontinuity at the
    ||aa|| = 2pi exp-map singularity for near-identity rotations).
    """
    from megba_tpu.io.g2o import _quat_xyzw_to_aa

    rng = np.random.default_rng(5)
    q = rng.standard_normal((64, 4))
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    aa_pos = _quat_xyzw_to_aa(q)
    aa_neg = _quat_xyzw_to_aa(-q)
    np.testing.assert_allclose(aa_neg, aa_pos, atol=1e-12)
    assert float(np.linalg.norm(aa_pos, axis=1).max()) <= np.pi + 1e-12
    # Matches the geo implementation it claims to mirror.
    ref = np.asarray(jax.vmap(geo.quaternion_to_angle_axis)(
        jnp.asarray(np.concatenate([q[:, 3:4], q[:, :3]], axis=1))))
    np.testing.assert_allclose(aa_pos, ref, atol=1e-6)
    # Near-identity negative-w quaternions stay near zero, both sides
    # of the small-angle branch.
    for eps in (1e-9, 2e-8, 1e-6):
        aa = _quat_xyzw_to_aa(np.array([eps, 0.0, 0.0, -1.0]))
        assert float(np.linalg.norm(aa)) < 1e-5, (eps, aa)


def test_compressed_roundtrip(tmp_path):
    """.gz and .bz2 g2o files read/write transparently (public datasets
    ship compressed)."""
    g = make_synthetic_pose_graph(num_poses=8, loop_closures=2, seed=6)
    graph = _graph_of(g)
    for ext in ("g2o", "g2o.gz", "g2o.bz2"):
        path = str(tmp_path / f"graph.{ext}")
        write_g2o(path, graph)
        back = read_g2o(path)
        np.testing.assert_array_equal(back.ids, graph.ids)
        np.testing.assert_allclose(_rotmats(back.poses[:, :3]),
                                   _rotmats(graph.poses[:, :3]), atol=1e-7)
        np.testing.assert_allclose(back.poses[:, 3:], graph.poses[:, 3:],
                                   atol=1e-7)
    # Compressed output is actually compressed.
    import gzip

    with gzip.open(str(tmp_path / "graph.g2o.gz"), "rt") as f:
        assert f.readline().startswith("VERTEX_SE3:QUAT")


def test_file_route_sharded_matches_single():
    """solve_g2o(world_size=8) on the virtual CPU mesh == world 1.

    The file route composes with the edge-sharded lowering (the g2o
    parser feeds the same solve_pgo boundary the sharded tests cover).
    """
    import dataclasses

    g = make_synthetic_pose_graph(num_poses=11, loop_closures=2,
                                  drift_noise=0.05, seed=8)
    buf = io.StringIO()
    write_g2o(buf, _graph_of(g))
    text = buf.getvalue()
    _, res1 = solve_g2o(io.StringIO(text), _option(max_iter=8))
    _, res8 = solve_g2o(
        io.StringIO(text),
        dataclasses.replace(_option(max_iter=8), world_size=8))
    np.testing.assert_allclose(float(res8.cost), float(res1.cost),
                               rtol=1e-9, atol=1e-18)
    assert int(res8.iterations) == int(res1.iterations)


def test_mixed_se2_se3_records():
    """One file mixing SE3:QUAT and SE2 records parses coherently:
    SE2 rows are lifted in place, ids/info interleave correctly."""
    text = """\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE2 1 1 0 0.5
VERTEX_SE3:QUAT 2 2 0 0 0 0 0.2474 0.9689
EDGE_SE3:QUAT 0 2 2 0 0 0 0 0.2474 0.9689 1 0 0 0 0 0 1 0 0 0 0 1 0 0 0 2 0 0 2 0 2
EDGE_SE2 0 1 1 0 0.5 3 0 0 3 0 3
"""
    graph = read_g2o(io.StringIO(text))
    assert not graph.se2  # mixed file counts as SE3
    np.testing.assert_array_equal(graph.ids, [0, 1, 2])
    # SE2 vertex lifted: z-rotation 0.5, in-plane translation.
    np.testing.assert_allclose(graph.poses[1], [0, 0, 0.5, 1, 0, 0],
                               atol=1e-9)
    # SE2 edge info lifted with unit out-of-plane rows; SE3 edge info
    # permuted/chart-scaled (rotation diag 2 -> 0.5, translation 1).
    np.testing.assert_allclose(np.diag(graph.info[1]),
                               [1, 1, 3, 3, 3, 1], atol=1e-12)
    np.testing.assert_allclose(np.diag(graph.info[0]),
                               [0.5, 0.5, 0.5, 1, 1, 1], atol=1e-4)
    _, res = solve_g2o(graph, _option(max_iter=10))
    assert float(res.cost) < 1e-10


def test_duplicate_vertex_id_raises_with_line_number():
    """A duplicate VERTEX id must fail loudly (ADVICE r4): last-wins
    parsing turns a malformed export into a plausible wrong graph."""
    text = """\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 0 1 0 0 0 0 0 1
"""
    with pytest.raises(ValueError, match=r"line 2: duplicate VERTEX id 0"):
        read_g2o(io.StringIO(text))
    # Cross-kind duplicates (SE2 reusing an SE3 id) are the same error.
    text = """\
VERTEX_SE3:QUAT 3 0 0 0 0 0 0 1
VERTEX_SE2 3 1 0 0.5
"""
    with pytest.raises(ValueError, match=r"line 2: duplicate VERTEX id 3"):
        read_g2o(io.StringIO(text))


def test_fix_records_round_trip_only_when_present():
    """write_g2o emits FIX only for graphs whose source declared FIX:
    the solver's default gauge anchor must not leak into the file
    (ADVICE r4)."""
    base = """\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 1 0 0 0 0 0 1
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 1 0 0 0 0 0 1 0 0 0 0 1 0 0 0 1 0 0 1 0 1
"""
    # No FIX in the input: the reader still anchors vertex 0 internally,
    # but a round trip must not invent a FIX record.
    g = read_g2o(io.StringIO(base))
    assert not g.had_fix and g.fixed[0]
    buf = io.StringIO()
    write_g2o(buf, g)
    assert "FIX" not in buf.getvalue()
    # With FIX in the input it round-trips verbatim.
    g2 = read_g2o(io.StringIO(base + "FIX 1\n"))
    assert g2.had_fix and g2.fixed[1] and not g2.fixed[0]
    buf2 = io.StringIO()
    write_g2o(buf2, g2)
    assert "FIX 1\n" in buf2.getvalue()
    # Programmatic graphs (dataclass default had_fix=True) keep writing
    # their anchors — only parser-produced defaults are suppressed.
    buf3 = io.StringIO()
    write_g2o(buf3, dataclasses.replace(g, had_fix=True))
    assert "FIX 0\n" in buf3.getvalue()


def test_short_lines_report_nonnegative_counts():
    """A bare tag line must not report 'got -1' (ADVICE r4)."""
    with pytest.raises(ValueError, match=r"got 0 \(1 tokens\)"):
        read_g2o(io.StringIO("VERTEX_SE3:QUAT\n"))
    with pytest.raises(ValueError, match=r"got 0 \(2 tokens\)"):
        read_g2o(io.StringIO("EDGE_SE3:QUAT 0\n"))


def test_fix_of_skipped_vertex_does_not_leak_default_anchor():
    """A FIX that only references skipped (unknown-tag) vertices must
    not mark the graph as file-anchored — otherwise the write path
    would emit the solver's fallback 'FIX 0' as if the file said so."""
    text = """\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 1 0 0 0 0 0 1
VERTEX_TRACKXYZ 5 0 0 0
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 1 0 0 0 0 0 1 0 0 0 0 1 0 0 0 1 0 0 1 0 1
FIX 5
"""
    g = read_g2o(io.StringIO(text))
    assert not g.had_fix and g.fixed[0]  # fallback anchor, ours
    buf = io.StringIO()
    write_g2o(buf, g)
    assert "FIX" not in buf.getvalue()


def test_solve_g2o_prior_ids_anchor_file_estimates():
    """solve_g2o(prior_ids=[...]) holds the named vertices softly at
    their FILE estimates (the surveying workflow), carries the gauge
    through the priors when the file declared no FIX, and returns poses
    sliced to the graph's own vertices."""
    g = make_synthetic_pose_graph(num_poses=10, loop_closures=3, seed=2)
    n = g.poses0.shape[0]
    # DRIFTED file estimates (poses0), exact measurements: the gauge is
    # free up to a rigid transform, so where pose 3 lands reveals
    # whether the prior actually acted — a dropped prior falls back to
    # anchoring pose 0 at ITS estimate and rigidly transports pose 3 to
    # poses0[0] o rel_gt(0,3), which differs from poses0[3] by the
    # accumulated drift.
    graph = _graph_of(g)
    graph = dataclasses.replace(graph, had_fix=False)
    opt = _option(max_iter=25)
    _, res = solve_g2o(graph, opt, prior_ids=[3], prior_weight=1e5)
    out = np.asarray(res.poses)
    assert out.shape[0] == n  # virtual anchors stripped
    # Pose 3 sits at its file estimate (the prior target) and the exact
    # measurements are satisfied around it.
    np.testing.assert_allclose(out[3], np.asarray(g.poses0)[3], atol=1e-4)
    assert float(res.cost) < 1e-6
    # Discriminating check: the dropped-prior fallback would land pose 3
    # at the rigid transport of pose 0's estimate, 0.247 away from the
    # prior target for this seed — assert we are NOT there.
    from megba_tpu.core.host_se3 import compose, relative

    transported = compose(
        g.poses0[0:1], relative(g.poses_gt[0:1], g.poses_gt[3:4]))[0]
    assert np.linalg.norm(out[3] - transported) > 0.1

    with pytest.raises(ValueError, match="not a vertex"):
        solve_g2o(graph, opt, prior_ids=[999])


def test_prior_gauge_decided_per_connected_component():
    """On a FIX-less multi-component graph, the defaulted anchor is
    dropped ONLY in components a prior reaches; every unreached
    component gets a hard anchor at one of its OWN poses (previously
    all-or-nothing: the kept fixed[0] fought the prior in its component
    and other components could end up entirely free)."""
    a = make_synthetic_pose_graph(num_poses=6, loop_closures=2, seed=3)
    b = make_synthetic_pose_graph(num_poses=6, loop_closures=2, seed=5)
    na = a.poses0.shape[0]
    n = na + b.poses0.shape[0]
    g2 = G2OGraph(
        poses=np.concatenate([a.poses0, b.poses0]),
        edge_i=np.concatenate([a.edge_i, b.edge_i + na]),
        edge_j=np.concatenate([a.edge_j, b.edge_j + na]),
        meas=np.concatenate([a.meas, b.meas]),
        info=np.tile(np.eye(6), (len(a.edge_i) + len(b.edge_i), 1, 1)),
        fixed=np.eye(1, n, 0, dtype=bool)[0],  # parser's default anchor
        ids=np.arange(n, dtype=np.int64), had_fix=False)
    _, res = solve_g2o(g2, _option(max_iter=30), prior_ids=[2],
                       prior_weight=1e5)
    out = np.asarray(res.poses)
    assert out.shape[0] == n
    # Exact measurements: both components converge to (near-)zero cost.
    assert float(res.cost) < 1e-6
    # Component A's gauge comes from the prior alone: pose 2 sits at its
    # file estimate instead of being dragged by a kept fixed[0] anchor.
    np.testing.assert_allclose(out[2], np.asarray(a.poses0)[2], atol=1e-4)
    # Component B was not reached by the prior: it is anchored at its
    # own first pose (index na), exactly at that pose's file estimate.
    np.testing.assert_allclose(out[na], np.asarray(b.poses0)[0], atol=1e-8)


# ---------------------------------------------------------------------------
# EDGE_SE3_PRIOR ingestion (ISSUE 13 satellite: unary-prior tags)
# ---------------------------------------------------------------------------

_DIAG21 = " ".join("1" if i in (0, 6, 11, 15, 18, 20) else "0"
                   for i in range(21))


def _prior_file(info=_DIAG21):
    return io.StringIO(
        "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
        "VERTEX_SE3:QUAT 1 1 0 0 0 0 0 1\n"
        "EDGE_SE3:QUAT 0 1 1.05 0 0 0 0 0 1 " + _DIAG21 + "\n"
        "EDGE_SE3_PRIOR 0 0.5 0 0 0 0 0 1 " + info + "\n")


def test_prior_records_parsed_with_chart():
    g = read_g2o(_prior_file())
    assert g.prior_idx.tolist() == [0]
    # measurement lands in OUR chart: [aa(3), t(3)]
    np.testing.assert_allclose(g.prior_meas[0],
                               [0, 0, 0, 0.5, 0, 0], atol=1e-12)
    # identity g2o info -> chart-corrected ours: rotation rows x 1/4
    np.testing.assert_allclose(np.diag(g.prior_info[0]),
                               [0.25, 0.25, 0.25, 1, 1, 1], atol=1e-12)


def test_prior_roundtrip_through_writer():
    g = read_g2o(_prior_file())
    buf = io.StringIO()
    write_g2o(buf, g)
    g2 = read_g2o(io.StringIO(buf.getvalue()))
    np.testing.assert_allclose(g2.prior_meas, g.prior_meas, atol=1e-9)
    np.testing.assert_allclose(g2.prior_info, g.prior_info, atol=1e-9)
    assert g2.prior_idx.tolist() == g.prior_idx.tolist()


def test_prior_malformed_counts_name_the_line():
    with pytest.raises(ValueError, match="line 2: EDGE_SE3_PRIOR needs"):
        read_g2o(io.StringIO(
            "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
            "EDGE_SE3_PRIOR 0 1 2 3\n"))
    # the 30-token upstream form (offset PARAMS id) is refused typed,
    # never silently mis-read
    with pytest.raises(ValueError, match="offset PARAMS id"):
        read_g2o(io.StringIO(
            "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
            "EDGE_SE3_PRIOR 0 99 0 0 0 0 0 0 1 " + _DIAG21 + "\n"))


def test_prior_unknown_vertex_and_nonfinite():
    with pytest.raises(ValueError, match="line 2: .*unknown vertex 7"):
        read_g2o(io.StringIO(
            "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
            "EDGE_SE3_PRIOR 7 0 0 0 0 0 0 1 " + _DIAG21 + "\n"))
    with pytest.raises(ValueError, match="line 2: .*non-finite"):
        read_g2o(io.StringIO(
            "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
            "EDGE_SE3_PRIOR 0 nan 0 0 0 0 0 1 " + _DIAG21 + "\n"))


@pytest.mark.slow
def test_solve_g2o_file_priors_anchor():
    """A file-carried prior acts exactly like the prior_ids machinery:
    the anchored pose lands on the PRIOR pose, not its drifted VERTEX
    estimate, and the between edge is satisfied around it."""
    g = read_g2o(_prior_file(
        " ".join("10000" if i in (0, 6, 11, 15, 18, 20) else "0"
                 for i in range(21))))
    assert not g.had_fix  # priors carry the gauge
    _, res = solve_g2o(g, _option(max_iter=25))
    out = np.asarray(res.poses)
    # prior pose: t = [0.5, 0, 0]; edge: pose1 = prior + [1.05, 0, 0]
    np.testing.assert_allclose(out[0, 3:], [0.5, 0, 0], atol=1e-3)
    np.testing.assert_allclose(out[1, 3:], [1.55, 0, 0], atol=1e-3)


# ---------------------------------------------------------------------------
# VERTEX/EDGE_SIM3:QUAT ingestion (ISSUE 13 satellite: sim(3) tags)
# ---------------------------------------------------------------------------

_DIAG28 = " ".join("1" if i in (0, 7, 13, 18, 22, 25, 27) else "0"
                   for i in range(28))


def _sim3_file():
    return io.StringIO(
        "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
        "VERTEX_SIM3:QUAT 1 1 0 0 0 0 0 1 2\n"
        "EDGE_SIM3:QUAT 0 1 1 0 0 0 0 0 1 2 " + _DIAG28 + "\n")


def test_sim3_parsed_into_log_scale_chart():
    g = read_g2o(_sim3_file())
    assert g.sim3 and g.poses.shape == (2, 7)
    np.testing.assert_allclose(g.poses[:, 6], [0.0, np.log(2.0)],
                               atol=1e-12)
    np.testing.assert_allclose(g.meas[0, 6], np.log(2.0), atol=1e-12)
    # identity file info -> chart-corrected: rotation rows x 1/4,
    # translation + log-scale rows unchanged
    np.testing.assert_allclose(np.diag(g.info[0]),
                               [0.25, 0.25, 0.25, 1, 1, 1, 1],
                               atol=1e-12)


def test_sim3_roundtrip_through_writer():
    from megba_tpu.factors.sim3 import make_synthetic_sim3_graph

    s = make_synthetic_sim3_graph(num_poses=8, loop_closures=2, seed=4)
    rng = np.random.default_rng(0)
    m = rng.normal(size=(len(s.edge_i), 7, 7))
    info = m @ np.swapaxes(m, 1, 2) + 7 * np.eye(7)
    g = G2OGraph(poses=s.poses0, edge_i=s.edge_i, edge_j=s.edge_j,
                 meas=s.meas, info=info,
                 fixed=np.eye(1, 8, 0, dtype=bool)[0],
                 ids=np.arange(8, dtype=np.int64), sim3=True,
                 had_fix=True)
    buf = io.StringIO()
    write_g2o(buf, g)
    g2 = read_g2o(io.StringIO(buf.getvalue()))
    assert g2.sim3 and g2.had_fix and g2.fixed[0]
    np.testing.assert_allclose(g2.poses, g.poses, atol=1e-7)
    np.testing.assert_allclose(g2.meas, g.meas, atol=1e-7)
    np.testing.assert_allclose(g2.info, g.info, rtol=1e-6, atol=1e-6)


def test_sim3_info_permutation_involution():
    from megba_tpu.io.g2o import _info7_g2o_to_ours, _info7_ours_to_g2o

    rng = np.random.default_rng(1)
    m = rng.normal(size=(5, 7, 7))
    info = m @ np.swapaxes(m, 1, 2)
    np.testing.assert_allclose(
        _info7_ours_to_g2o(_info7_g2o_to_ours(info)), info, atol=1e-12)


def test_sim3_adversarial_records():
    # token counts, with line numbers
    with pytest.raises(ValueError, match="line 1: VERTEX_SIM3:QUAT needs"):
        read_g2o(io.StringIO("VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1\n"))
    with pytest.raises(ValueError, match="line 2: EDGE_SIM3:QUAT needs"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
            "EDGE_SIM3:QUAT 0 0 1 2 3\n"))
    # non-positive scales (vertex and edge)
    with pytest.raises(ValueError, match="non-positive scale"):
        read_g2o(io.StringIO("VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 -2\n"))
    with pytest.raises(ValueError, match="line 3: .*non-positive scale"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
            "VERTEX_SIM3:QUAT 1 0 0 0 0 0 0 1 1\n"
            "EDGE_SIM3:QUAT 0 1 0 0 0 0 0 0 1 0 " + _DIAG28 + "\n"))
    # duplicate vertex, unknown vertex, non-finite
    with pytest.raises(ValueError, match="line 2: duplicate VERTEX"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"))
    with pytest.raises(ValueError, match="unknown vertex 9"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
            "EDGE_SIM3:QUAT 0 9 1 0 0 0 0 0 1 1 " + _DIAG28 + "\n"))
    with pytest.raises(ValueError, match="non-finite"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 inf 0 0 0 1 1\n"))


def test_sim3_mixing_with_se3_refused_both_orders():
    with pytest.raises(ValueError, match="line 2: .*cannot be mixed"):
        read_g2o(io.StringIO(
            "VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1\n"
            "VERTEX_SIM3:QUAT 1 0 0 0 0 0 0 1 1\n"))
    with pytest.raises(ValueError, match="line 2: .*cannot be mixed"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
            "VERTEX_SE2 1 0 0 0\n"))
    with pytest.raises(ValueError, match="line 2: .*cannot be mixed"):
        read_g2o(io.StringIO(
            "VERTEX_SIM3:QUAT 0 0 0 0 0 0 0 1 1\n"
            "EDGE_SE3_PRIOR 0 0 0 0 0 0 0 1 " + _DIAG21 + "\n"))


def test_sim3_solve_dispatch_guards():
    """SE(3)-only conveniences are refused typed on sim(3) graphs
    (host-side, before anything compiles)."""
    g = read_g2o(_sim3_file())
    with pytest.raises(ValueError, match="not supported for .*sim"):
        solve_g2o(g, _option(), prior_ids=[0])
    with pytest.raises(ValueError, match="spanning_tree.*not supported"):
        solve_g2o(g, _option(), init="spanning_tree")


@pytest.mark.slow
def test_solve_g2o_sim3_end_to_end():
    """A drifted sim(3) file solves through the sim3_between factor to
    (near-)zero cost with the scale trail recovered."""
    from megba_tpu.factors.sim3 import make_synthetic_sim3_graph

    s = make_synthetic_sim3_graph(num_poses=16, loop_closures=5, seed=2)
    n_e = len(s.edge_i)
    g = G2OGraph(poses=s.poses0, edge_i=s.edge_i, edge_j=s.edge_j,
                 meas=s.meas, info=np.tile(np.eye(7), (n_e, 1, 1)),
                 fixed=np.eye(1, 16, 0, dtype=bool)[0],
                 ids=np.arange(16, dtype=np.int64), sim3=True)
    buf = io.StringIO()
    write_g2o(buf, g)
    g2 = read_g2o(io.StringIO(buf.getvalue()))
    graph, res = solve_g2o(g2, _option(max_iter=25))
    assert graph.sim3
    assert float(res.cost) < 1e-6
    np.testing.assert_allclose(np.asarray(res.poses)[:, 6],
                               s.poses_gt[:, 6] - s.poses_gt[0, 6],
                               atol=1e-3)
