"""Compiled-program auditor (megba_tpu/analysis/program_audit.py).

Two layers of coverage:

- the CLEAN TREE: every canonical program passes all four audit passes
  and the committed ANALYSIS_BUDGET.json baseline;
- SEEDED VIOLATING PROGRAMS: each pass demonstrably fires — a
  callback-in-jit program (transfer pass), a program with a gratuitous
  extra psum in its PCG-scoped while body (collective census), an
  f64-leaking f32 program (dtype census), a program whose declared
  donation never materialises (donation pass), and an inflated budget
  fixture (budget gate), so a pass that silently stops matching is
  itself a test failure.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megba_tpu.analysis import audit as audit_cli
from megba_tpu.analysis import budget as budget_mod
from megba_tpu.analysis import hlo, program_audit
from megba_tpu.parallel.mesh import EDGE_AXIS, make_mesh, shard_map


# The factor-registry canonical programs (ISSUE 13) ride the SLOW lane
# here: tier-1 sits ~90s from its budget and each extra program costs a
# trace + parse even with the compile cache warm.  They are still
# audited on every full run — by scripts/lint.sh gate 4 (audit --check
# covers ALL programs) and by the slow-marked test below.
FACTOR_PROGRAMS = frozenset({
    "ba_rig_single_f32", "ba_radial_single_f32",
    "prior_single_f64", "pgo_sim3_single_f64",
})

# The 2-D mesh canonical program (ISSUE 14) rides the slow lane for the
# same reason: a fresh world-4 SPMD trace per tier-1 run is exactly the
# compile volume the budget can't absorb.  Like the factor programs it
# is still audited on every full run (lint gate 4 + the slow test).
# The bf16 MXU pipeline programs (ISSUE 15) join them: two more SPMD
# traces (world 2 + world 4) the tier-1 budget can't absorb — audited
# every full run by lint gate 4 and the slow bf16 test below.
SLOW_PROGRAMS = FACTOR_PROGRAMS | {
    "ba_2d_w4_f32", "ba_bf16_w2_f32", "ba_bf16_2d_w4_f32"}


@pytest.fixture(scope="module")
def audits():
    """The historical canonical programs, lowered + compiled once per
    test module (the persistent compile cache makes repeat runs
    cheap); the factor-registry and 2-D mesh programs audit in the
    slow lane."""
    names = [n for n in program_audit.program_specs()
             if n not in SLOW_PROGRAMS]
    return program_audit.audit_all(names)


def _fake_spec(**kw):
    base = dict(name="seeded", float_family="f32", world=1, pcg_psums=0,
                donate_leaves=(), build=lambda: None)
    base.update(kw)
    return program_audit.ProgramSpec(**base)


def _audit_of(spec, lowered, compiled=None):
    return program_audit.ProgramAudit(
        spec=spec,
        stablehlo=lowered.as_text(),
        compiled_text="" if compiled is None else compiled.as_text(),
        flops=-1.0, bytes_accessed=-1.0, peak_temp_bytes=-1.0,
        argument_bytes=-1.0, output_bytes=-1.0)


# ---------------------------------------------------------------------------
# Clean tree
# ---------------------------------------------------------------------------

def test_clean_tree_every_pass_green(audits):
    for name, audit in audits.items():
        assert audit.violations() == [], (
            f"{name} violates the compiled-program contract")


def test_clean_tree_matches_committed_budget(audits):
    baseline = budget_mod.load_baseline()
    assert baseline, "ANALYSIS_BUDGET.json missing — run audit --update"
    # Tier-1 audits the historical set; the factor and 2-D mesh
    # programs' baseline parity rides the slow tests below + lint gate 4
    # (which always compares the FULL set, including the "no longer
    # audited" check).
    baseline = {n: v for n, v in baseline.items()
                if n not in SLOW_PROGRAMS}
    measured = {n: a.metrics() for n, a in audits.items()}
    assert budget_mod.compare(baseline, measured) == []


def test_fused_off_canonical_programs_kernel_free(audits):
    """Dark-landing pin (fused edge-pipeline kernels): with
    `SolverOption.fused_kernels` at its default (off), no canonical
    program may carry a Pallas kernel — a Pallas call lowers to a
    `tpu_custom_call`/mosaic custom_call, so the census catching one
    here means the fused path leaked into a default-option lowering."""
    for name, audit in audits.items():
        census = hlo.custom_call_census(audit.stablehlo_ops)
        kernels = [t for t in census
                   if "tpu_custom_call" in t or "mosaic" in t.lower()
                   or "pallas" in t.lower()]
        assert kernels == [], (
            f"{name}: Pallas custom_call in a fused-off canonical "
            f"program: {kernels}")


def test_fused_off_lowering_byte_identical(audits):
    """Explicitly passing `fused_kernels=False` must produce the SAME
    program, byte for byte, as leaving the field at its default — the
    committed ANALYSIS_BUDGET.json entries describe both spellings.
    (The fused machinery lands dark: DualPlans' optional fused fields
    stay None and never reach the traced program.)"""
    import dataclasses as _dc

    from megba_tpu.common import JacobianMode
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = program_audit._ba_problem()
    option = program_audit._ba_option()
    assert option.solver_option.fused_kernels is False  # the default
    explicit = _dc.replace(option, solver_option=_dc.replace(
        option.solver_option, fused_kernels=False))
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    lowered = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                         s.pt_idx, explicit, use_tiled=True,
                         lower_only=True)
    assert lowered.as_text() == audits["ba_tiled_f32"].stablehlo


@pytest.mark.slow
def test_factor_programs_clean_and_on_budget():
    """The factor-registry canonical programs (ISSUE 13): every audit
    pass green and baseline parity, including the census expectations
    (zero collectives single-device, clean dtype family, donation
    materialised)."""
    audits = program_audit.audit_all(sorted(FACTOR_PROGRAMS))
    for name, audit in audits.items():
        assert audit.violations() == [], name
    baseline = {n: v for n, v in budget_mod.load_baseline().items()
                if n in FACTOR_PROGRAMS}
    measured = {n: a.metrics() for n, a in audits.items()}
    assert budget_mod.compare(baseline, measured) == []


@pytest.mark.slow
def test_mesh2d_program_subgroup_census_and_bytes_law():
    """The ISSUE 14 acceptance pin: `ba_2d_w4_f32` is clean on every
    audit pass (which includes the replica-group census — every
    PCG-body collective subgroup-scoped, the exact kind->count pattern
    matched), sits on its committed budget, and moves strictly fewer
    bytes per CG step than the 1-D all-reduce scaling law predicts at
    world 4 (measured against ba_sharded_w2_f32, not just the committed
    numbers)."""
    audits = program_audit.audit_all(["ba_2d_w4_f32", "ba_sharded_w2_f32"])
    a2d = audits["ba_2d_w4_f32"]
    assert a2d.violations() == []
    baseline = {"ba_2d_w4_f32": budget_mod.load_baseline()["ba_2d_w4_f32"]}
    assert budget_mod.compare(
        baseline, {"ba_2d_w4_f32": a2d.metrics()}) == []
    # Subgroup scope, asserted directly on the parsed groups: no body
    # collective spans the world.
    body = a2d.pcg_body_collectives()
    assert body, "2-D program must have PCG-body collectives"
    for op in body:
        assert op.group_size() is not None, op.where()
        assert op.group_size() < 4, (op.where(), op.replica_groups)
    # Bytes law: the 1-D body's two all-reduces cost 2B(g-1)/g per
    # device over summed operand bytes B; the world-2 measurement IS B,
    # so the world-4 1-D prediction is 1.5 B.
    b1d = audits["ba_sharded_w2_f32"].pcg_body_collective_bytes()
    b2d = a2d.pcg_body_collective_bytes()
    assert b2d < b1d * 2.0 * (4 - 1) / 4, (b2d, b1d)


def test_bf16_machinery_off_census_is_clean(audits):
    """Dtype-census regression (ISSUE 15 satellite): with the bf16
    machinery merged but OFF, every historical canonical program's
    StableHLO carries ZERO bf16 tensors — identical census to the
    pre-merge tree (the committed ANALYSIS_BUDGET entries, compared
    byte-for-byte by test_clean_tree_matches_committed_budget + lint
    gate 4, pin the rest of the byte-identity claim)."""
    for name, audit in audits.items():
        census = hlo.dtype_census(audit.stablehlo)
        assert "bf16" not in census, (name, census)
        assert "bf16" not in audit.stablehlo, name


@pytest.mark.slow
def test_bf16_programs_clean_halved_bytes_and_real_bf16_compute():
    """The ISSUE 15 acceptance pin: both bf16 canonical programs are
    green on every pass (incl. the allowed-surface census), sit on
    their committed budgets, price `collective_bytes_per_sp` at
    EXACTLY half their f32 counterparts', and actually carry bf16
    compute (multiplies / f32-accumulating dot_generals) — the
    silent-upcast guard measured live, not just structurally."""
    audits = program_audit.audit_all(
        ["ba_bf16_w2_f32", "ba_bf16_2d_w4_f32"])
    baseline = budget_mod.load_baseline()
    for name, audit in audits.items():
        assert audit.violations() == [], (name, audit.violations())
        assert budget_mod.compare(
            {name: baseline[name]}, {name: audit.metrics()}) == []
    # Exactly half the committed f32 counterparts — the halved-wire
    # acceptance criterion, against the SAME committed numbers the
    # budget gate enforces.
    for cand, ctrl in (("ba_bf16_w2_f32", "ba_sharded_w2_f32"),
                       ("ba_bf16_2d_w4_f32", "ba_2d_w4_f32")):
        assert (baseline[cand]["collective_bytes_per_sp"]
                == 0.5 * baseline[ctrl]["collective_bytes_per_sp"]), (
            cand, ctrl)
        assert (audits[cand].pcg_body_collective_bytes()
                == baseline[cand]["collective_bytes_per_sp"])
    # Live bf16-compute presence + the declared in-body payloads.
    for name, audit in audits.items():
        ops = hlo.bf16_stablehlo_ops(audit.stablehlo)
        n_mul = sum(1 for op in ops
                    if op.kind == "multiply" and op.result_dtype == "bf16")
        n_dot = sum(1 for op in ops if op.kind == "dot_general")
        assert n_mul >= 1, name  # bf16-operand products exist
        assert n_dot >= 1, name  # the bf16 M⁻¹ apply exists
        assert all(op.result_dtype != "bf16" for op in ops
                   if op.kind == "dot_general"), name  # f32 accumulation
        declared = [op for op in hlo.stablehlo_collective_payloads(
            audit.stablehlo) if op.while_depth >= 2]
        assert declared and all(
            op.result_dtype == "bf16" for op in declared), (name, declared)
    # The 2-D bf16 program keeps the subgroup contract on top.
    body = audits["ba_bf16_2d_w4_f32"].pcg_body_collectives()
    assert body and all(op.group_size(4) < 4 for op in body)


# ---------------------------------------------------------------------------
# bf16 surface pass units (pure text — no lowering)
# ---------------------------------------------------------------------------

def _surface_audit(stablehlo, **surface_kw):
    spec = _fake_spec(bf16_surface=program_audit.Bf16Surface(**surface_kw))
    return program_audit.ProgramAudit(
        spec=spec, stablehlo=stablehlo, compiled_text="",
        flops=-1.0, bytes_accessed=-1.0, peak_temp_bytes=-1.0,
        argument_bytes=-1.0, output_bytes=-1.0)


_CLEAN_BF16 = """\
func.func public @main(%arg0: tensor<4xf32>) -> tensor<4xf32> {
  %0 = stablehlo.convert %arg0 : (tensor<4xf32>) -> tensor<4xbf16>
  %1 = stablehlo.multiply %0, %0 : tensor<4xbf16>
  %2 = stablehlo.convert %1 : (tensor<4xbf16>) -> tensor<4xf32>
  return %2 : tensor<4xf32>
}
"""


def test_bf16_surface_clean_program_passes():
    assert _surface_audit(_CLEAN_BF16).bf16_surface_violations() == []


def test_bf16_surface_none_means_wrong_family():
    # Without a declared surface the historical rule applies: bf16 in
    # an f32 program is a dtype leak (pass 3), and the surface pass
    # stays silent rather than double-reporting.
    a = program_audit.ProgramAudit(
        spec=_fake_spec(), stablehlo=_CLEAN_BF16, compiled_text="",
        flops=-1.0, bytes_accessed=-1.0, peak_temp_bytes=-1.0,
        argument_bytes=-1.0, output_bytes=-1.0)
    assert a.bf16_surface_violations() == []
    assert any("bf16" in v for v in a.dtype_violations())


def test_bf16_surface_flags_disallowed_kind():
    bad = _CLEAN_BF16.replace(
        "stablehlo.multiply %0, %0 : tensor<4xbf16>",
        "stablehlo.exponential %0 : tensor<4xbf16>")
    out = _surface_audit(bad).bf16_surface_violations()
    assert any("outside the declared surface" in v for v in out), out


def test_bf16_surface_flags_bf16_accumulation():
    bad = _CLEAN_BF16.replace(
        "stablehlo.multiply %0, %0 : tensor<4xbf16>",
        "stablehlo.add %0, %0 : tensor<4xbf16>")
    out = _surface_audit(bad).bf16_surface_violations()
    assert any("bf16 accumulation" in v for v in out), out


def test_bf16_surface_flags_bf16_dot_result():
    bad = _CLEAN_BF16.replace(
        "stablehlo.multiply %0, %0 : tensor<4xbf16>",
        "stablehlo.dot_general %0, %0, contracting_dims = [0] x [0] "
        ": (tensor<4xbf16>, tensor<4xbf16>) -> tensor<bf16>")
    out = _surface_audit(bad).bf16_surface_violations()
    assert any("ACCUMULATES in bf16" in v for v in out), out


def test_bf16_surface_flags_f64_convert():
    bad = _CLEAN_BF16.replace(
        "stablehlo.convert %1 : (tensor<4xbf16>) -> tensor<4xf32>",
        "stablehlo.convert %1 : (tensor<4xbf16>) -> tensor<4xf64>")
    out = _surface_audit(bad).bf16_surface_violations()
    assert any("family leak" in v for v in out), out


def test_bf16_surface_flags_silent_upcast():
    # All-convert program: bf16 tensors exist but every product was
    # upcast away — zero bf16 compute ops must FAIL, not pass quietly.
    quiet = _CLEAN_BF16.replace(
        "stablehlo.multiply %0, %0 : tensor<4xbf16>",
        "stablehlo.reshape %0 : (tensor<4xbf16>) -> tensor<4xbf16>")
    out = _surface_audit(quiet).bf16_surface_violations()
    assert any("silently upcast" in v for v in out), out


def test_bf16_surface_collective_gate():
    coll = """\
func.func public @main(%arg0: tensor<4xf32>) -> tensor<4xf32> {
  %0 = stablehlo.convert %arg0 : (tensor<4xf32>) -> tensor<4xbf16>
  %1 = stablehlo.multiply %0, %0 : tensor<4xbf16>
  %2 = "stablehlo.all_reduce"(%1) <{replica_groups = dense<[[0, 1]]> : tensor<1x2xi64>}> ({
  ^bb0(%a: tensor<bf16>, %b: tensor<bf16>):
    %s = stablehlo.add %a, %b : tensor<bf16>
    stablehlo.return %s : tensor<bf16>
  }) : (tensor<4xbf16>) -> tensor<4xbf16>
  %3 = stablehlo.convert %2 : (tensor<4xbf16>) -> tensor<4xf32>
  return %3 : tensor<4xf32>
}
"""
    # Undeclared collectives: both the payload and (implicitly) the
    # scalar region add are flagged.
    out = _surface_audit(coll).bf16_surface_violations()
    assert any("without a declared bf16_collectives" in v
               for v in out), out
    # Declared: the payload and its rank-0 reduction add are the
    # contract, not a violation (no compiled body here, so only the
    # text checks run).
    out2 = _surface_audit(coll, collectives=True).bf16_surface_violations()
    assert not any("without a declared" in v or "accumulation" in v
                   for v in out2), out2


def test_stablehlo_collective_payload_parser_forms():
    payloads = hlo.stablehlo_collective_payloads(
        """\
func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
  %0 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64}> : (tensor<4xbf16>) -> tensor<8xbf16>
  %1 = stablehlo.while(%iterArg = %arg0) : tensor<8xf32>
   cond {
    stablehlo.return %c : tensor<i1>
  } do {
    %2 = "stablehlo.all_reduce"(%iterArg) <{replica_groups = dense<[[0, 1]]> : tensor<1x2xi64>}> ({
    ^bb0(%a: tensor<bf16>, %b: tensor<bf16>):
      %s = stablehlo.add %a, %b : tensor<bf16>
      stablehlo.return %s : tensor<bf16>
    }) : (tensor<8xbf16>) -> tensor<8xbf16>
    stablehlo.return %2 : tensor<8xf32>
  }
  return %1 : tensor<8xf32>
}
""")
    by_kind = {p.kind: p for p in payloads}
    ag = by_kind["all_gather"]  # inline form, outside any while
    assert (ag.result_dtype, ag.result_elems, ag.while_depth) == (
        "bf16", 8, 0)
    ar = by_kind["all_reduce"]  # region form, inside the while body
    assert (ar.result_dtype, ar.result_elems) == ("bf16", 8)
    assert ar.while_depth == 1


def test_collective_census_matches_analytic_expectation(audits):
    # Two reductions per CG step for the Schur solve (hlp + hpl inside
    # S·p), one for PGO's matrix-free H·x; single-device programs carry
    # no collectives at all.
    assert len(audits["ba_sharded_w2_f32"].pcg_body_collectives()) == 2
    # Inexact LM (adaptive forcing + warm starts) must add ZERO
    # collectives to the CG step: the traced eta_k is pure carry math
    # and the warm-start products live outside the while body.
    assert len(audits["ba_forcing_w2_f32"].pcg_body_collectives()) == 2
    # Fault containment (RobustOption guards) must be collective-free
    # too: breakdown detection reads already-psum-reduced scalars and
    # the in-loop restart reuses the body's single matvec slot.
    assert len(audits["ba_guarded_w2_f32"].pcg_body_collectives()) == 2
    # The two-level preconditioner's coarse CORRECTION must add zero
    # in-body collectives: the cycle runs on the materialised G/A_c
    # (its V and G build psums live outside the while body).
    assert len(audits["ba_twolevel_w2_f32"].pcg_body_collectives()) == 2
    # Same for the RECURSIVE multilevel hierarchy: every level beyond
    # the first is a replicated dense Galerkin (no collectives at all),
    # so the while-body census is still exactly the two S·p psums.
    assert len(audits["ba_multilevel_w2_f32"].pcg_body_collectives()) == 2
    assert len(audits["pgo_sharded_w2_f64"].pcg_body_collectives()) == 1
    for name in ("ba_single_f32", "ba_tiled_f32", "pgo_single_f64"):
        assert audits[name].collectives == [], name
    # psum is the only prescribed collective: everything the SPMD
    # programs emit is an all-reduce.
    for name in ("ba_sharded_w2_f32", "ba_forcing_w2_f32",
                 "ba_guarded_w2_f32", "ba_twolevel_w2_f32",
                 "ba_multilevel_w2_f32", "pgo_sharded_w2_f64"):
        kinds = {op.kind for op in audits[name].collectives}
        assert kinds == {"all_reduce"}, (name, kinds)


def test_twolevel_build_psums_live_outside_the_pcg_body(audits):
    # The coarse build is allowed exactly its V and G all-reduces, once
    # per PCG solve, scoped megba.precond_coarse_build — NOT inside
    # megba.pcg_core's while body.  The MULTILEVEL hierarchy adds no
    # build psums beyond those two: all deeper Galerkin levels are
    # replicated dense contractions (asserted structurally here, not
    # just by the total census).
    for prog in ("ba_twolevel_w2_f32", "ba_multilevel_w2_f32"):
        aud = audits[prog]
        build_ops = [op for op in aud.collectives
                     if "precond_coarse_build" in (op.op_name or "")]
        assert len(build_ops) == 2, (
            prog, [op.op_name for op in build_ops])
        for op in build_ops:
            assert "pcg_core/while" not in op.op_name, (prog, op.op_name)


def test_guarded_program_adds_no_collectives_vs_unguarded(audits):
    # "Guards are free" at the census level: the guarded SPMD program's
    # TOTAL all-reduce count equals the unguarded one's — detection
    # piggybacks on reductions that already exist.
    n_guarded = len(audits["ba_guarded_w2_f32"].collectives)
    n_plain = len(audits["ba_sharded_w2_f32"].collectives)
    assert n_guarded == n_plain, (n_guarded, n_plain)


def test_donation_materialised_in_compiled_executables(audits):
    # flat_solve donates (cameras, points); solve_pgo donates poses.
    assert hlo.aliased_parameters(
        audits["ba_single_f32"].compiled_text) == {0, 1}
    assert hlo.aliased_parameters(
        audits["pgo_single_f64"].compiled_text) == {0}


def test_summary_is_json_roundtrippable(audits):
    for audit in audits.values():
        doc = json.loads(json.dumps(audit.summary(), sort_keys=True))
        assert doc["program"] == audit.spec.name
        assert doc["violations"] == []
        assert doc["metrics"]["flops"] > 0


# ---------------------------------------------------------------------------
# Pass 1 seeded violation: a callback inside a jitted program
# ---------------------------------------------------------------------------

def test_transfer_pass_fires_on_callback_in_jit():
    def leaky(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2.0,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    lowered = jax.jit(leaky).lower(np.ones((4,), np.float32))
    audit = _audit_of(_fake_spec(name="seeded_callback"), lowered)
    bad = audit.transfer_violations()
    assert bad, "callback-in-jit must fail the transfer pass"
    assert "callback" in bad[0]
    assert "seeded_callback" in bad[0]


def test_transfer_pass_fires_on_verbose_program():
    # The REAL production path: a verbose=True solve carries the
    # observability iteration-line callback — the audit must see it
    # (canonical audited programs are verbose=False and stay clean).
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = make_synthetic_bal(num_cameras=3, num_points=12, obs_per_point=3,
                           seed=2, dtype=np.float32)
    option = ProblemOption(dtype=np.float32,
                           algo_option=AlgoOption(max_iter=2),
                           solver_option=SolverOption(max_iter=4))
    lowered = flat_solve(
        make_residual_jacobian_fn(), s.cameras0, s.points0, s.obs,
        s.cam_idx, s.pt_idx, option, use_tiled=False, verbose=True,
        lower_only=True)
    ops = hlo.parse_stablehlo_ops(lowered.as_text())
    assert hlo.transfer_ops(ops), "verbose program must show its callback"


# ---------------------------------------------------------------------------
# Pass 2 seeded violation: a gratuitous extra psum per CG step
# ---------------------------------------------------------------------------

def _pcg_like_program(n_psums: int):
    """A shard_map'ed program with `n_psums` psums inside a while body
    scoped exactly like the real PCG core (megba.pcg_core)."""
    mesh = make_mesh(2)

    @jax.named_scope("megba.pcg_core")
    def fake_pcg(v):
        def cond(c):
            return c[0] < 3

        def body(c):
            k, x = c
            for i in range(n_psums):
                x = jax.lax.psum(x * (1.0 + i), EDGE_AXIS)
            return k + 1, x

        return jax.lax.while_loop(cond, body, (jnp.int32(0), v))

    def prog(x):
        _, out = fake_pcg(x)
        return jax.lax.psum(out, EDGE_AXIS)  # "LM bookkeeping" sync

    sharded = shard_map(prog, mesh=mesh, in_specs=P(EDGE_AXIS),
                        out_specs=P())
    return jax.jit(sharded).lower(np.ones((8,), np.float32))


def test_collective_census_fires_on_extra_psum():
    spec = _fake_spec(name="seeded_extra_psum", world=2, pcg_psums=2)
    lowered = _pcg_like_program(n_psums=3)
    audit = _audit_of(spec, lowered, lowered.compile())
    bad = audit.collective_violations()
    assert bad, "an extra psum per CG step must fail the census"
    assert "3 all-reduce(s) inside the PCG while body" in bad[0]
    assert "expectation is 2" in bad[0]
    # ...and the offending ops are named with their scope paths.
    assert "megba.pcg_core/while/body" in bad[0]


def test_collective_census_green_on_expected_psums():
    spec = _fake_spec(name="seeded_ok_psums", world=2, pcg_psums=2)
    lowered = _pcg_like_program(n_psums=2)
    audit = _audit_of(spec, lowered, lowered.compile())
    assert audit.collective_violations() == []


def test_collective_census_rejects_collectives_in_single_device_spec():
    spec = _fake_spec(name="seeded_unsharded", world=1, pcg_psums=0)
    lowered = _pcg_like_program(n_psums=1)
    audit = _audit_of(spec, lowered, lowered.compile())
    bad = audit.collective_violations()
    assert bad and "single-device" in bad[0]


# ---------------------------------------------------------------------------
# Pass 3 seeded violations: dtype leak + dropped donation
# ---------------------------------------------------------------------------

def test_dtype_census_fires_on_f64_leak():
    def leaky(x):
        return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)

    lowered = jax.jit(leaky).lower(np.ones((8,), np.float32))
    audit = _audit_of(_fake_spec(name="seeded_f64_leak"), lowered)
    bad = audit.dtype_violations()
    assert bad, "an f64 op in an f32 solve must fail the dtype census"
    assert "f64" in bad[0] and "f32 solve" in bad[0]


def test_dtype_census_fires_on_weak_literal_where():
    # The exact historical leak the clean tree had: a Python float in a
    # `where` branch materialises as tensor<f64> + convert under x64.
    def weak(x):
        return jnp.where(x > 0, x, 1.0)

    lowered = jax.jit(weak).lower(np.ones((8,), np.float32))
    audit = _audit_of(_fake_spec(name="seeded_weak_literal"), lowered)
    assert audit.dtype_violations()


def test_donation_pass_fires_when_declared_donation_missing():
    lowered = jax.jit(lambda x: x + 1.0).lower(np.ones((8,), np.float32))
    spec = _fake_spec(name="seeded_no_alias", donate_leaves=(0,))
    audit = _audit_of(spec, lowered, lowered.compile())
    bad = audit.donation_violations()
    assert bad and "[0]" in bad[0] and "did not materialise" in bad[0]


def test_donation_pass_fires_on_undeclared_alias():
    lowered = jax.jit(lambda x: x + 1.0,
                      donate_argnums=(0,)).lower(np.ones((8,), np.float32))
    spec = _fake_spec(name="seeded_surprise_alias", donate_leaves=())
    audit = _audit_of(spec, lowered, lowered.compile())
    bad = audit.donation_violations()
    assert bad and "without a declared donation" in bad[0]


# ---------------------------------------------------------------------------
# Pass 4 seeded violation: budget fixture broken beyond tolerance
# ---------------------------------------------------------------------------

def test_budget_gate_fires_on_inflated_baseline(audits):
    measured = {n: a.metrics() for n, a in audits.items()}
    doctored = {n: dict(m) for n, m in measured.items()}
    # Tolerance-breaking: the baseline claims ~9x fewer FLOPs than the
    # program costs, so the measurement reads as a >15% regression.
    doctored["ba_single_f32"]["flops"] = measured["ba_single_f32"]["flops"] / 9
    violations = budget_mod.compare(doctored, measured)
    assert violations, "a 9x flops drift must break the budget"
    assert any("ba_single_f32" in v and "flops" in v for v in violations)
    # ...and metrics inside tolerance stay silent.
    assert not any("pgo_single_f64" in v for v in violations)


def test_budget_gate_exact_match_on_collective_count(audits):
    measured = {n: a.metrics() for n, a in audits.items()}
    doctored = {n: dict(m) for n, m in measured.items()}
    doctored["ba_sharded_w2_f32"]["all_reduce_count"] += 1  # one extra sync
    violations = budget_mod.compare(doctored, measured)
    assert any("ba_sharded_w2_f32" in v and "all_reduce_count" in v
               for v in violations)


def test_budget_gate_degrades_loudly_when_metric_unavailable(audits):
    # A backend without cost/memory analysis yields no measurement for a
    # gated metric: that must be an explicit violation, not a silent
    # skip and not a fake "-100% improvement" from a -1 sentinel.
    measured = {n: dict(a.metrics()) for n, a in audits.items()}
    del measured["ba_single_f32"]["peak_temp_bytes"]
    violations = budget_mod.compare(
        {n: dict(m) for n, m in measured.items()}
        | {"ba_single_f32": dict(audits["ba_single_f32"].metrics())},
        measured)
    assert any("ba_single_f32" in v and "peak_temp_bytes" in v
               and "unavailable" in v for v in violations)
    # ...and the -1 sentinel itself never reaches the metrics dict.
    crippled = program_audit.ProgramAudit(
        spec=audits["ba_single_f32"].spec, stablehlo="", compiled_text="",
        flops=-1.0, bytes_accessed=-1.0, peak_temp_bytes=-1.0,
        argument_bytes=-1.0, output_bytes=-1.0)
    # The census-derived metrics (counts + bytes-moved) come from the
    # HLO text, and the declared per-S·p axes from the spec itself —
    # neither needs the cost analysis, so both survive the cripple.
    assert set(crippled.metrics()) == {"all_reduce_count",
                                      "other_collective_count",
                                      "collective_bytes_per_sp",
                                      "flops_per_sp",
                                      "bytes_touched_per_sp"}


def test_audit_cli_check_exits_nonzero_on_broken_budget(
        audits, tmp_path, capsys):
    # End-to-end CLI contract (satellite): a tolerance-breakingly edited
    # ANALYSIS_BUDGET.json makes `audit --check` exit nonzero with the
    # program and metric named.  Scoped to one (cached) program so the
    # in-process run costs one re-lower, not five.
    measured = {"ba_single_f32": audits["ba_single_f32"].metrics()}
    doctored = {n: dict(m) for n, m in measured.items()}
    doctored["ba_single_f32"]["flops"] = measured["ba_single_f32"]["flops"] / 9
    path = tmp_path / "budget.json"
    budget_mod.write_baseline(doctored, str(path))

    rc = audit_cli.main(["--check", "--baseline", str(path),
                         "--program", "ba_single_f32"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "ba_single_f32" in err and "flops" in err

    # --update rewrites the baseline from measurements; --check then
    # passes on the same tree.
    rc = audit_cli.main(["--update", "--baseline", str(path),
                         "--program", "ba_single_f32"])
    assert rc == 0
    rc = audit_cli.main(["--check", "--baseline", str(path),
                         "--program", "ba_single_f32"])
    assert rc == 0


# ---------------------------------------------------------------------------
# Parser units (pure text, no jax)
# ---------------------------------------------------------------------------

def test_custom_call_census_counts_targets():
    text = """\
module @jit_fn {
  func.func public @main(%arg0: tensor<4xf32>) -> tensor<4xf32> {
    %0 = stablehlo.custom_call @tpu_custom_call(%arg0) : (tensor<4xf32>) -> tensor<4xf32>
    %1 = stablehlo.custom_call @tpu_custom_call(%0) : (tensor<4xf32>) -> tensor<4xf32>
    %2 = stablehlo.custom_call @Sharding(%1) : (tensor<4xf32>) -> tensor<4xf32>
    %3 = stablehlo.add %2, %2 : tensor<4xf32>
    return %3 : tensor<4xf32>
  }
}
"""
    census = hlo.custom_call_census(hlo.parse_stablehlo_ops(text))
    assert census == {"tpu_custom_call": 2, "Sharding": 1}


def test_custom_call_census_in_summary(audits):
    doc = json.loads(json.dumps(audits["ba_single_f32"].summary()))
    assert "custom_calls" in doc
    assert all(isinstance(v, int) for v in doc["custom_calls"].values())


def test_sp_budget_axes_priced_and_gated():
    """The declared analytical axes: present for every canonical
    program, exact-gated (tolerance 0.0), and the fused pricing arm
    strictly undercuts the unfused one on identical geometry (the
    transient round-trips are the only difference)."""
    from megba_tpu.analysis import edge_budget

    for name, spec in program_audit.program_specs().items():
        d = dict(spec.sp_budget or ())
        assert d.get("flops_per_sp", 0) > 0, name
        assert d.get("bytes_touched_per_sp", 0) > 0, name
    assert budget_mod.TOLERANCES["flops_per_sp"] == 0.0
    assert budget_mod.TOLERANCES["bytes_touched_per_sp"] == 0.0
    unfused = edge_budget.schur_sp_budget(4, 9, 24, 3, 2, 2048)
    fused = edge_budget.schur_sp_budget(4, 9, 24, 3, 2, 2048,
                                        transient_roundtrips=False)
    assert fused["flops_per_sp"] == unfused["flops_per_sp"]
    assert fused["bytes_touched_per_sp"] < unfused["bytes_touched_per_sp"]
    # bf16 operand tiles halve the coupling-row traffic, never the flops.
    bf16 = edge_budget.schur_sp_budget(4, 9, 24, 3, 2, 2048,
                                       operand="bf16")
    assert bf16["flops_per_sp"] == unfused["flops_per_sp"]
    assert bf16["bytes_touched_per_sp"] < unfused["bytes_touched_per_sp"]


def test_stablehlo_while_depth_tracking():
    text = """\
module @jit_fn {
  func.func public @main(%arg0: tensor<4xf32>) -> tensor<4xf32> {
    %0 = stablehlo.add %arg0, %arg0 : tensor<4xf32>
    %1:2 = stablehlo.while(%iterArg = %0, %iterArg_0 = %0) : tensor<4xf32>, tensor<4xf32>
     cond {
      %c = stablehlo.constant dense<true> : tensor<i1>
      stablehlo.return %c : tensor<i1>
    } do {
      %2 = stablehlo.multiply %iterArg, %iterArg : tensor<4xf32>
      %3:2 = stablehlo.while(%iterArg2 = %2, %iterArg_3 = %2) : tensor<4xf32>, tensor<4xf32>
       cond {
        %c2 = stablehlo.constant dense<true> : tensor<i1>
        stablehlo.return %c2 : tensor<i1>
      } do {
        %4 = stablehlo.subtract %iterArg2, %iterArg2 : tensor<4xf32>
        stablehlo.return %4, %4 : tensor<4xf32>, tensor<4xf32>
      }
      stablehlo.return %3#0, %3#1 : tensor<4xf32>, tensor<4xf32>
    }
    %5 = stablehlo.negate %1#0 : tensor<4xf32>
    return %5 : tensor<4xf32>
  }
}
"""
    ops = hlo.parse_stablehlo_ops(text)
    depth = {(op.kind, op.line): op.while_depth for op in ops}
    assert depth[("add", 3)] == 0
    assert depth[("multiply", 9)] == 1
    assert depth[("subtract", 15)] == 2
    assert depth[("negate", 20)] == 0


def test_stablehlo_one_line_while_does_not_leak_depth():
    # Generic print form: a while whose regions open AND close on one
    # line is self-contained — it must not push a region frame that
    # inflates while_depth for everything after it.
    text = (
        "module {\n"
        "  func.func @main(%arg0: tensor<f32>) -> tensor<f32> {\n"
        '    %0 = "stablehlo.while"(%arg0) ({ '
        '"stablehlo.return"(%arg0) : (tensor<f32>) -> () }, { '
        '"stablehlo.return"(%arg0) : (tensor<f32>) -> () })'
        " : (tensor<f32>) -> tensor<f32>\n"
        "    %1 = stablehlo.negate %0 : tensor<f32>\n"
        "    return %1 : tensor<f32>\n"
        "  }\n"
        "}\n")
    ops = hlo.parse_stablehlo_ops(text)
    negate = [op for op in ops if op.kind == "negate"]
    assert negate and negate[0].while_depth == 0


def test_input_output_alias_parser():
    header = ("HloModule jit_fn, is_scheduled=true, input_output_alias="
              "{ {5}: (0, {}, may-alias), {1}: (2, {}, must-alias) }, "
              "entry_computation_layout={(f32[4]{0})->f32[4]{0}}\n")
    assert hlo.aliased_parameters(header) == {0, 2}
    assert hlo.aliased_parameters("HloModule jit_fn\n") == frozenset()


def test_compiled_hlo_parser_reads_metadata():
    line = ('  %all-reduce.8 = f32[9,24]{1,0} all-reduce(f32[9,24]{1,0} '
            '%slice), channel_id=19, replica_groups={{0,1}}, '
            'to_apply=%region_82, metadata={op_name="jit(fn)/jit(main)/'
            'while/body/megba.pcg/megba.pcg_core/while/body/psum" '
            'source_file="x.py"}\n')
    ops = hlo.parse_compiled_ops(line)
    assert len(ops) == 1
    (op,) = ops
    assert op.kind == "all_reduce"
    assert op.result_dtype == "f32" and op.result_elems == 216
    assert program_audit.PCG_BODY_MARK in op.op_name


def test_compiled_hlo_parser_reads_tuple_result_collectives():
    # XLA's AllReduceCombiner merges adjacent all-reduces into ONE op
    # with a tuple result type; the census must not lose it.
    line = ('  %all-reduce = (f32[9,24]{1,0}, f32[24]{0}) all-reduce('
            'f32[9,24]{1,0} %a, f32[24]{0} %b), replica_groups={{0,1}}, '
            'to_apply=%region, metadata={op_name="jit(fn)/'
            'megba.pcg/megba.pcg_core/while/body/psum"}\n')
    ops = hlo.parse_compiled_ops(line)
    assert [op.kind for op in ops] == ["all_reduce"]
    assert ops[0].result_dtype == "f32"
    assert program_audit.PCG_BODY_MARK in ops[0].op_name


def test_transfer_target_classification():
    mk = lambda target: hlo.HloOp(kind="custom_call", line=1, text="",
                                  target=target)
    assert hlo.transfer_ops([mk("xla_python_cpu_callback")])
    assert hlo.transfer_ops([mk("xla_ffi_python_cpu_callback")])
    assert not hlo.transfer_ops([mk("lapack_spotrf_ffi")])
    assert not hlo.transfer_ops([mk("Sharding")])
    # Sanctioned targets are exempt.
    assert not hlo.transfer_ops([mk("xla_python_cpu_callback")],
                                allow=("xla_python_cpu_callback",))
