"""Two-process multi-host smoke test over localhost CPU.

The reference cannot do this at all (single-process ncclCommInitAll,
handle_manager.cpp:17-22).  Here two OS processes join one
jax.distributed cluster and run a cross-process psum — the exact
collective the sharded solve uses.  The workers live in
tests/_multihost_worker.py; this test only orchestrates them so the
pytest process itself never initialises a second distributed runtime.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_localhost_cluster():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # 1 CPU device per process
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid} OK" in out, out
