"""Two-process multi-host smoke test over localhost CPU.

The reference cannot do this at all (single-process ncclCommInitAll,
handle_manager.cpp:17-22).  Here two OS processes join one
jax.distributed cluster and run a cross-process psum — the exact
collective the sharded solve uses.  The workers live in
tests/_multihost_worker.py; this test only orchestrates them so the
pytest process itself never initialises a second distributed runtime.
"""

import pytest

import os
import socket
import subprocess
import sys

from megba_tpu.parallel.multihost import (
    cpu_cross_process_collectives_available,
)

# Explicit platform-capability gate: the plain XLA:CPU client cannot run
# multiprocess computations at all ("Multiprocess computations aren't
# implemented on the CPU backend"); the workers select jaxlib's gloo TCP
# collectives, which not every jaxlib build ships.  Without gloo this
# lane skips — loudly, naming the limitation — instead of failing
# tier-1 on a backend that can never pass it.
needs_cpu_collectives = pytest.mark.skipif(
    not cpu_cross_process_collectives_available(),
    reason="jaxlib CPU client lacks gloo TCP collectives: multiprocess "
           "computations aren't implemented on the plain CPU backend")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@needs_cpu_collectives
def test_two_process_localhost_cluster():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # 1 CPU device per process
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid} OK" in out, out


@pytest.mark.slow
@needs_cpu_collectives
def test_two_process_sharded_solve_matches_single_process():
    """Two processes x 2 virtual CPU devices run ONE sharded LM solve
    through the real pipeline (flat_solve -> shard_map over the global
    4-device mesh, inputs lifted via make_array_from_callback) and
    must match the single-process world-4 solve bit-for-bit-ish (f64).

    This is the end-to-end upgrade of the psum smoke above: it
    exercises host prep + globalization + the full jitted LM program
    across process boundaries, the capability the reference's
    single-process ncclCommInitAll can never express
    (handle_manager.cpp:17-22).
    """
    import re

    import numpy as np

    port = _free_port()
    worker = os.path.join(
        os.path.dirname(__file__), "_multihost_solve_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the worker pins its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(port), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    pat = re.compile(
        r"worker (\d) SOLVE cost ([0-9.eE+-]+) initial ([0-9.eE+-]+) "
        r"iters (\d+)")
    got = {}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        m = pat.search(out)
        assert m, f"worker {pid} printed no solve line:\n{out}"
        got[int(m.group(1))] = (float(m.group(2)), float(m.group(3)),
                                int(m.group(4)))
    # Replicated outputs: both processes must report identical results.
    assert got[0] == got[1], got

    # Single-process world-4 reference on the same problem (the pytest
    # process has 8 virtual devices via conftest).
    from megba_tpu.common import (
        AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = make_synthetic_bal(
        num_cameras=6, num_points=90, obs_per_point=5, seed=7,
        param_noise=3e-2, pixel_noise=0.3, dtype=np.float64)
    option = ProblemOption(
        dtype=np.float64,
        world_size=4,
        compute_kind=ComputeKind.IMPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=6),
        solver_option=SolverOption(max_iter=20, tol=1e-12),
    )
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    ref = flat_solve(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)
    np.testing.assert_allclose(got[0][0], float(ref.cost), rtol=1e-9)
    np.testing.assert_allclose(got[0][1], float(ref.initial_cost),
                               rtol=1e-12)
    assert got[0][2] == int(ref.iterations)

    # PGO family over the same cluster: workers printed a PGO line too.
    pgo_pat = re.compile(
        r"worker (\d) PGO cost ([0-9.eE+-]+) initial ([0-9.eE+-]+) "
        r"iters (\d+)")
    pgo = {}
    for pid, out in enumerate(outs):
        m = pgo_pat.search(out)
        assert m, f"worker {pid} printed no PGO line:\n{out}"
        pgo[int(m.group(1))] = (float(m.group(2)), float(m.group(3)),
                                int(m.group(4)))
    assert pgo[0] == pgo[1], pgo

    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    g = make_synthetic_pose_graph(num_poses=24, loop_closures=6, seed=3)
    pgo_opt = ProblemOption(
        dtype=np.float64, world_size=4,
        algo_option=AlgoOption(max_iter=5),
        solver_option=SolverOption(max_iter=15, tol=1e-12),
    )
    pref = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, pgo_opt)
    np.testing.assert_allclose(pgo[0][0], float(pref.cost), rtol=1e-9)
    np.testing.assert_allclose(pgo[0][1], float(pref.initial_cost),
                               rtol=1e-12)
    assert pgo[0][2] == int(pref.iterations)
