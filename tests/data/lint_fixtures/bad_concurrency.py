"""Seeded BAD concurrency patterns — every block below must produce a
finding (tests/test_concurrency.py pins the exact counts; lane 6 of
scripts/lint.sh asserts the linter exits non-zero on this file for each
of the three concurrency rule ids).

NOT executed anywhere: this module exists purely as linter input.
"""

import queue
import threading
import time

_REG_LOCK = threading.Lock()


class UnguardedCounter:
    """Declared contract violated: one unlocked write, one unlocked
    read of a `guarded-by` field."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # megba: guarded-by(_lock)

    def bump(self):
        with self._lock:
            self.hits += 1

    def racy_write(self):
        self.hits += 1  # guarded-by: write without the lock

    def racy_read(self):
        return self.hits  # guarded-by: read without the lock


class InferredRace:
    """No pragma: 5 of 6 accesses hold `_mu` (>= 80%, >= 5 accesses)
    and the class is thread-reachable, so the guard is inferred; the
    unlocked read in `peek` flags."""

    def __init__(self):
        self._mu = threading.Lock()
        self.total = 0
        self._thread = threading.Thread(target=self._work, daemon=True)

    def _work(self):
        with self._mu:
            self.total += 1
            self.total += 2
            self.total += 3
            self.total += 4
            self.total += 5

    def peek(self):
        return self.total  # guarded-by: inferred guard not held


class Deadlock:
    """Classic AB/BA inversion — the lock-order pass prints the
    witness path."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass


class CondReacquire:
    """The cycle exists ONLY because `Condition.wait` re-acquires its
    condition LAST: `_locked_step` runs with `_cond` held at entry
    (private helper, only called under it), nests `_gate`, then waits —
    the wakeup re-acquires `_cond` while still holding `_gate`."""

    def __init__(self):
        self._cond = threading.Condition()
        self._gate = threading.Lock()

    def step(self):
        with self._cond:
            self._locked_step()

    def _locked_step(self):
        with self._gate:  # lock-order: _cond -> _gate
            self._cond.wait(0.01)  # re-acquire edge: _gate -> _cond


def fetch_result(fut):
    with _REG_LOCK:
        return fut.result()  # blocking-under-lock: Future.result


class BlockyServer:
    """The serve-loop stall shapes: blocking I/O inside the critical
    section."""

    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def drain_one(self):
        with self._lock:
            return self._q.get()  # blocking-under-lock: queue get

    def lazy_stop(self, worker):
        with self._lock:
            worker.join()  # blocking-under-lock: thread join
            time.sleep(0.5)  # blocking-under-lock: long sleep

    def pump(self, conn):
        with self._lock:
            return conn.recv(4096)  # blocking-under-lock: pipe recv
