"""Seeded GOOD concurrency patterns — every block below must stay
silent under the guarded-by / lock-order / blocking-under-lock rules
(lane 6 of scripts/lint.sh runs the linter over this file and fails on
ANY finding; tests/test_concurrency.py pins zero).

NOT executed anywhere: this module exists purely as linter input.
"""

import os
import threading
import time

_STATE_LOCK = threading.Lock()


class GuardedCounter:
    """The declared contract, honoured — plus both escape hatches:
    a field settled in __init__ (safe publication) and an explicit
    `allow-unguarded` pragma on an approximate fast-path read."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # megba: guarded-by(_lock)
        self.name = "counter"  # settled in __init__: publication is safe

    def bump(self):
        with self._lock:
            self.hits += 1

    def read(self):
        with self._lock:
            return self.hits

    def gauge_hint(self):
        # An intentionally approximate read (monitoring display only).
        return self.hits  # megba: allow-unguarded

    def label(self):
        return self.name  # read-only after __init__: no guard needed


class LockedHelper:
    """`_append_locked` is private and only ever called under the lock:
    the entry-held fixed point grants it the guard, no pragma needed."""

    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # megba: guarded-by(_lock)
        threading.Thread(target=self.run, daemon=True).start()

    def run(self):
        with self._lock:
            self._append_locked(1)

    def _append_locked(self, x):
        self.items.append(x)  # caller holds the lock


class CondWaiter:
    """Sanctioned Condition use: waiting on the HELD condition releases
    it — no stall, no ordering edge."""

    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False  # megba: guarded-by(_cond)

    def wait_ready(self):
        with self._cond:
            while not self.ready:
                self._cond.wait(0.1)

    def set_ready(self):
        with self._cond:
            self.ready = True
            self._cond.notify_all()


class OrderedLocks:
    """Two locks, always nested in one global order: no inversion."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0  # megba: guarded-by(_b)

    def one(self):
        with self._a:
            with self._b:
                self.n += 1

    def two(self):
        with self._a:
            with self._b:
                self.n -= 1


def metadata(d, key):
    with _STATE_LOCK:
        return d.get(key, None)  # dict.get(key): not a queue get


def label(parts):
    with _STATE_LOCK:
        return ", ".join(parts)  # str.join: not a thread join


def artifact_path(root, name):
    with _STATE_LOCK:
        return os.path.join(root, name)  # path assembly, no blocking


def tiny_pause():
    with _STATE_LOCK:
        time.sleep(0.01)  # below the 0.05 s stall threshold
