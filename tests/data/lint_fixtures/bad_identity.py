"""Seeded BAD program-identity patterns for the lint lane's must-fire
gate (scripts/lint.sh lane 7).

NOT executed anywhere: this module exists purely as linter input for
analysis/identity.py — each block below is a deliberately broken
miniature of the repo's option/key machinery, and every class of
finding the three identity rules detect appears at least once:

- stale-program: a lowering-path read of a strip-listed field with no
  strip in the same function (flat_solve), and a builder whose static
  key omits its option (_build_single_solve);
- cache-split: declared option fields no lowering code ever reads and
  no pragma declares (debug_port, scratch_limit_mb);
- key-surface-drift: a partial strip + non-conforming helper
  (_sans_telemetry), a hardcoded exclusion tuple disagreeing with the
  registry (_config_mismatches), an un-stripped memoised cache front
  (flat_solve), and an operand branched on inside traced code (fn).

tests/test_identity.py pins the exact finding counts per rule, so a
rule that silently stops matching is itself a regression.
"""

import dataclasses
import functools
from typing import Optional

import jax

OBSERVABILITY_FIELDS = ("telemetry", "metrics")


def static_key(*parts):
    return "|".join(repr(p) for p in parts)


@dataclasses.dataclass(frozen=True)
class SolverOption:
    max_iter: int = 100
    bf16: bool = False
    # cache-split: never lowering-read, not stripped, no declared
    # intent — fragments every key surface for nothing.
    scratch_limit_mb: int = 0


@dataclasses.dataclass(frozen=True)
class ProblemOption:
    dtype: str = "float32"
    # cache-split: host-only debug knob nobody reads and nobody
    # declared.
    debug_port: int = 0
    solver_option: SolverOption = dataclasses.field(
        default_factory=SolverOption)
    telemetry: Optional[str] = None
    metrics: bool = False


def _sans_telemetry(option):
    # key-surface-drift: partial strip (clears telemetry, leaves
    # metrics) — and as a declared strip helper it conforms to nothing.
    return dataclasses.replace(option, telemetry=None)


def _config_mismatches(recorded, current):
    # key-surface-drift: hardcoded exclusion tuple disagreeing with
    # OBSERVABILITY_FIELDS.
    return sorted(k for k in set(recorded) | set(current)
                  if k not in ("telemetry",)
                  and recorded.get(k) != current.get(k))


def _build_single_solve(residual_jac_fn, option):
    # stale-program: the static key omits `option`, hiding every field
    # the traced body reads from the program's identity.
    key = static_key(residual_jac_fn, "solve.single")

    def fn(x, mask):
        scale = 2.0 if option.solver_option.bf16 else 1.0
        steps = option.solver_option.max_iter
        if option.dtype == "float32":  # static branch: legal
            scale = scale + steps
        if mask:  # key-surface-drift: operand-as-static branch
            return x * scale
        return x

    return jax.jit(fn), key


_cached_single_solve = functools.lru_cache(maxsize=8)(_build_single_solve)


def flat_solve(residual_jac_fn, x, option: ProblemOption):
    # stale-program: reads the strip-listed sink on the lowering path
    # and never strips it; key-surface-drift: fronts the memoised
    # program cache with the un-stripped option.
    sink = option.telemetry
    prog, key = _cached_single_solve(residual_jac_fn, option)
    return prog(x, None), key, sink
