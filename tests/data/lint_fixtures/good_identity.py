"""Seeded GOOD program-identity patterns: the must-stay-silent half of
lint lane 7 (scripts/lint.sh).

NOT executed anywhere: linter input only.  This module mirrors
bad_identity.py with every contract honoured, and deliberately
exercises the sanctioned shapes and declared-intent hatches so a rule
that over-matches fails the gate:

- the consume-and-strip shape (flat_solve resolves the sink, then
  routes through the canonical strip helper before the cache front);
- a conforming strip-helper delegation chain (_sans_telemetry ->
  strip_observability);
- an exclusion test derived from the registry (_config_mismatches)
  AND a hardcoded tuple that exactly EQUALS it (_legacy_mismatches —
  agreement is not drift);
- both field-scoped pragmas (a lowering-relevant program-family
  selector, a key-exempt host-only knob);
- a static key that includes the option, and an operand used only
  through the sanctioned `is None` presence check.
"""

import dataclasses
import functools
from typing import Optional

import jax

OBSERVABILITY_FIELDS = ("telemetry", "metrics")


def static_key(*parts):
    return "|".join(repr(p) for p in parts)


def strip_observability(option):
    if option.telemetry is not None or option.metrics:
        return dataclasses.replace(option, telemetry=None, metrics=False)
    return option


@dataclasses.dataclass(frozen=True)
class SolverOption:
    # Program-family selector no lowering code branches on yet:
    # declared lowering-relevant, so cache-split stays quiet.
    solver_kind: int = 0  # megba: lowering-relevant(solver_option.solver_kind)
    max_iter: int = 100
    bf16: bool = False


@dataclasses.dataclass(frozen=True)
class ProblemOption:
    dtype: str = "float32"
    # True host-only knob: declared key-exempt.
    trace_dir: Optional[str] = None  # megba: key-exempt(trace_dir)
    solver_option: SolverOption = dataclasses.field(
        default_factory=SolverOption)
    telemetry: Optional[str] = None
    metrics: bool = False


def _sans_telemetry(option):
    # Conforming helper: routes through the canonical strip helper.
    return strip_observability(option)


def _config_mismatches(recorded, current):
    # The exclusion test derives from the one registry: cannot drift.
    return sorted(k for k in set(recorded) | set(current)
                  if k not in OBSERVABILITY_FIELDS
                  and recorded.get(k) != current.get(k))


def _legacy_mismatches(recorded):
    # Hardcoded tuple that EQUALS the registry: agreement, not drift.
    return sorted(k for k in recorded
                  if k not in ("telemetry", "metrics"))


def _build_single_solve(residual_jac_fn, option):
    # The static key carries the (stripped) option: every field the
    # traced body reads is part of the program's identity.
    key = static_key(residual_jac_fn, option, "solve.single")

    def fn(x, mask):
        scale = 2.0 if option.solver_option.bf16 else 1.0
        steps = option.solver_option.max_iter
        if mask is not None:  # sanctioned presence check
            x = x * scale
        return x + 0.0 * steps

    return jax.jit(fn), key


_cached_single_solve = functools.lru_cache(maxsize=8)(_build_single_solve)


def flat_solve(residual_jac_fn, x, option: ProblemOption):
    # Consume-and-strip: resolve the sink, clear the observability
    # fields in this same function, THEN hit the memoised cache front.
    sink = option.telemetry
    option = strip_observability(option)
    if option.dtype == "float32":
        x = x
    prog, key = _cached_single_solve(residual_jac_fn, option)
    return prog(x, None), key, sink
