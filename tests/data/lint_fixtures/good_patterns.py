"""Seeded known-GOOD patterns: the same idioms as bad_patterns.py done
right, plus the legitimate edge cases each rule must NOT flag.  The
linter must stay silent on this file — a false positive here is a
regression in a rule, caught by tests/test_analysis.py."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from megba_tpu.utils.timing import monotonic_s, wall_unix


def host_driver(cams_np):
    # np.* / float() are fine OUTSIDE jit-reachable code: this is the
    # host lowering layer, exactly where numpy belongs.
    cams = np.ascontiguousarray(cams_np.T)
    scale = float(np.linalg.norm(cams))
    return cams / scale


def hot_body(cams, pts):  # megba: jit-entry
    # pure jnp math, weak Python scalars (which do NOT promote dtypes)
    y = cams * 2.0 + 1.0
    return y + jnp.sum(pts)


def explicit_dtypes(n, dtype):
    a = jnp.zeros((n, 3), dtype)
    b = jnp.ones(n, dtype=dtype)
    c = jnp.arange(n, dtype=jnp.int32)
    d = jnp.array([1.0, 2.0, 3.0], dtype=dtype)
    e = jnp.full((n,), 0, jnp.int32)
    f = jnp.eye(3, dtype=dtype)
    return a, b, c, d, e, f


def inherited_dtype(x, c, s):
    # jnp.array over expressions inherits its operands' dtype — the
    # rule must not demand redundant annotations here.
    rot = jnp.array([[c, -s], [s, c]])
    return rot @ x


def allowed_np(x):  # megba: jit-entry
    # pragma suppression: trace-time static shape math, deliberate
    n = np.prod(x.shape)  # megba: allow-np-in-jit
    return x.reshape(n)


def safe_cast(x):  # megba: jit-entry
    # the blessed alternative to scalar-promotion: asarray to the
    # array's own dtype keeps the expression dtype-stable
    two = jnp.asarray(2.0, x.dtype)
    return x * two


def donate_handoff(cameras, points, obs):
    prog = jax.jit(lambda c, p, o: (c + o, p), donate_argnums=(0, 1))
    out_c, out_p = prog(cameras, points, obs)
    # only the RESULTS are read after the call; the donated operands
    # are never touched again
    return out_c * 2.0, out_p


def donate_rebound(cameras, obs):
    prog = jax.jit(lambda c, o: c + o, donate_argnums=(0,))
    cameras = prog(cameras, obs)
    # `cameras` was rebound to the result — reading it now is fine
    return cameras + 1.0


def donate_multiline_call(cameras, points, obs):
    prog = jax.jit(lambda c, p, o: (c + o, p), donate_argnums=(0, 1))
    # the call's own arguments on continuation lines are not
    # reads-after-donation
    out_c, out_p = prog(
        cameras,
        points, obs)
    return out_c, out_p


def sanctioned_clocks(deadline):
    # raw-clock done right: durations via monotonic_s(), epoch stamps
    # via wall_unix(); time.monotonic deadline arithmetic and
    # time.sleep are not clock READS and must stay unflagged
    t0 = monotonic_s()
    time.sleep(0.0)
    late = time.monotonic() > deadline
    return monotonic_s() - t0, wall_unix(), late


def weak_literal_done_right(x, cond, lo, hi):
    # the blessed alternatives: *_like constructors / dtype-pinned
    # scalars in the leaky positions; plain arithmetic literals and
    # jnp.maximum/minimum literals promote weakly and are NOT flagged
    a = jnp.where(cond, x, jnp.zeros_like(x))
    b = jnp.where(cond, jnp.ones_like(x), x)
    c = jnp.clip(x, jnp.asarray(0.0, x.dtype), jnp.asarray(1.0, x.dtype))
    d = jnp.where(cond, x, x * 2.0)  # literal in arithmetic: weak, fine
    e = jnp.maximum(x, 1e-30)  # probed clean (no wide constant)
    f = jnp.clip(x, lo, hi)
    return a, b, c, d, e, f
