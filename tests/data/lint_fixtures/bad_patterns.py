"""Seeded known-BAD patterns for megba_tpu.analysis.lint.

Every rule must fire at least once on this file — tests/test_analysis.py
pins the exact (rule, function) pairs, so a rule that silently stops
matching breaks the suite, not the codebase.  This file is never
imported or executed; it only exists to be parsed.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback
from time import perf_counter as _pc


def leaky_callback(x):
    # host-callback: callback outside observability/ and utils/debug.py
    jax.debug.callback(print, x)
    jax.debug.print("x = {}", x)
    io_callback(print, None, x)
    return x


def hot_body(cams, pts):  # megba: jit-entry
    # np-in-jit: host numpy + coercions inside a jit-reachable function
    norms = np.linalg.norm(cams, axis=0)
    scale = float(norms[0])
    first = pts[0].item()
    return cams * scale + first


def helper_called_from_hot(x):
    # np-in-jit via reachability: not an entry itself, but hot_entry
    # below references it.
    return np.sqrt(x)


def hot_entry(x):  # megba: jit-entry
    return helper_called_from_hot(x) + 1.0


def implicit_dtypes(n):
    # implicit-dtype: constructors with nothing to inherit a dtype from
    a = jnp.zeros((n, 3))
    b = jnp.ones(n)
    c = jnp.arange(n)
    d = jnp.array([1.0, 2.0, 3.0])
    e = jnp.full((n,), 0)
    f = jnp.eye(3)
    return a, b, c, d, e, f


def promoting_math(x):  # megba: jit-entry
    # scalar-promotion: strongly-typed scalar ctors in array arithmetic
    y = x * np.float64(2.0)
    z = jnp.int64(3) + x
    return y, z


def donated_then_reused(cameras, points, obs):
    prog = jax.jit(lambda c, p, o: (c + o, p), donate_argnums=(0, 1))
    out_c, out_p = prog(cameras, points, obs)
    # donated-reuse: cameras' buffer was deleted by the call above
    leak = cameras + 1.0
    return out_c, out_p, leak


def raw_clock_reads():
    # raw-clock: wall/perf reads outside the clock homes (utils/timing,
    # observability/) — including through import aliases
    started = time.time()
    t0 = time.perf_counter()
    t1 = _pc()
    return started, t0, t1


def weak_literal_leaks(x, cond):
    # weak-literal: bare float literals in jnp.where branches / clip
    # bounds materialise f64-under-x64 constant tensors in f32 programs
    a = jnp.where(cond, x, 0.0)
    b = jnp.where(cond, 1.0, x)
    c = jnp.clip(x, 0.0, 1.0)
    d = jnp.where(cond, x * x, -1.0)
    return a, b, c, d
