"""Opt-in real-TPU lane (VERDICT round-1 item 7).

Run with: MEGBA_TPU_TESTS=1 python -m pytest tests/ -m tpu -p no:cacheprovider

Rules of engagement with the single-client axon tunnel (see
utils/backend.py and the round-1/2 postmortems): FOREGROUND only, one
process at a time, never kill a test mid-claim — so this module keeps
each case small (seconds of device time; the ~66 ms tunnel sync and the
one-off remote compile dominate).  Everything here is float32 — f64 on
TPU is emulated and pinned to CPU by the production pipeline.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def tpu_backend():
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip(f"no TPU backend (got {jax.default_backend()})")
    return jax.devices()[0]


def _mini_bal(seed=0, num_cameras=12, num_points=160, obs_per_point=5):
    from megba_tpu.io.synthetic import make_synthetic_bal

    return make_synthetic_bal(
        num_cameras=num_cameras, num_points=num_points,
        obs_per_point=obs_per_point, seed=seed, param_noise=3e-2,
        pixel_noise=0.3, dtype=np.float32)


def test_e2e_solve_fp32(tpu_backend):
    # One end-to-end LM solve on the chip: converges and matches the CPU
    # result to f32 tolerance.
    import jax.numpy as jnp

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.bal import BALFile
    from megba_tpu.solve import solve_bal

    s = _mini_bal()
    bal = BALFile(cameras=s.cameras0, points=s.points0, obs=s.obs,
                  cam_idx=s.cam_idx, pt_idx=s.pt_idx)
    option = ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=10, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=60, tol=1e-8, refuse_ratio=1e30))
    _, res = solve_bal(bal, option)
    assert np.isfinite(float(res.cost))
    assert float(res.cost) < 0.05 * float(res.initial_cost)
    assert int(res.accepted) > 0


def test_segtile_kernels_on_mosaic(tpu_backend):
    # The tiled reduce / expand / fused-build kernels must lower through
    # real Mosaic and match f64-accumulated numpy ground truth.
    import jax.numpy as jnp

    from megba_tpu.ops.segtiles import (
        build_tile_plan,
        device_plan,
        jtj_grad_reduce,
        tile_expand,
        tile_reduce,
    )

    rng = np.random.default_rng(0)
    n, cd, od, nc = 8192, 9, 2, 57
    cam_idx = np.sort(rng.integers(0, nc, n)).astype(np.int32)
    plan = build_tile_plan(cam_idx, nc, tile=512, block=64)
    dp = device_plan(plan)

    # tile_reduce vs numpy scatter-add
    data = rng.standard_normal((3, n)).astype(np.float32)
    slot_data = (data[:, plan.perm] * plan.mask).astype(np.float32)
    ref = np.zeros((3, nc))
    for f_ in range(3):
        np.add.at(ref[f_], cam_idx, data[f_].astype(np.float64))
    got = np.asarray(tile_reduce(jnp.asarray(slot_data), dp))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    # tile_expand vs numpy take
    table = rng.standard_normal((cd, nc)).astype(np.float32)
    ge = np.asarray(tile_expand(jnp.asarray(table), dp))
    real = plan.mask > 0
    np.testing.assert_array_equal(
        ge[:, real], table[:, cam_idx[plan.perm[real]]])

    # fused J^T J + gradient build vs f64 numpy
    jc = rng.standard_normal((od * cd, plan.n_slots)).astype(np.float32)
    r = rng.standard_normal((od, plan.n_slots)).astype(np.float32)
    jc *= plan.mask
    r *= plan.mask
    h_rows, g_rows = jtj_grad_reduce(
        jnp.asarray(jc), jnp.asarray(r), dp, use_kernels=True)
    jc64, r64 = jc.astype(np.float64), r.astype(np.float64)
    seg = plan.seg
    hpp_ref = np.zeros((cd * cd, nc))
    g_ref = np.zeros((cd, nc))
    for a in range(cd):
        for b in range(cd):
            row = sum(jc64[o * cd + a] * jc64[o * cd + b] for o in range(od))
            np.add.at(hpp_ref[a * cd + b], seg, row)
        row = -sum(jc64[o * cd + a] * r64[o] for o in range(od))
        np.add.at(g_ref[a], seg, row)
    scale = np.abs(hpp_ref).max()
    assert np.abs(np.asarray(h_rows) - hpp_ref).max() < 1e-5 * scale
    assert np.abs(np.asarray(g_rows) - g_ref).max() < 1e-5 * np.abs(g_ref).max()


def test_mixed_precision_solve(tpu_backend):
    # bf16 coupling-product solve on hardware lands at the same basin as
    # full f32.
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.bal import BALFile
    from megba_tpu.solve import solve_bal

    s = _mini_bal(seed=3)
    bal = BALFile(cameras=s.cameras0, points=s.points0, obs=s.obs,
                  cam_idx=s.cam_idx, pt_idx=s.pt_idx)

    def run(mixed):
        option = ProblemOption(
            dtype=np.float32, mixed_precision_pcg=mixed,
            algo_option=AlgoOption(max_iter=12, epsilon1=1e-9,
                                   epsilon2=1e-12),
            solver_option=SolverOption(max_iter=80, tol=1e-10,
                                       refuse_ratio=1e30))
        _, res = solve_bal(bal, option)
        return res

    full = run(False)
    mixed = run(True)
    assert float(mixed.cost) < 0.05 * float(mixed.initial_cost)
    np.testing.assert_allclose(
        float(mixed.cost), float(full.cost), rtol=5e-2)


def test_coupling_kernels_on_mosaic(tpu_backend):
    # The fused coupling-product halves (implicit PCG's hot kernels:
    # gather+J.x expand, J^T.u+segment reduce) must lower through real
    # Mosaic and match f64 numpy.
    import jax.numpy as jnp

    from megba_tpu.ops.segtiles import (
        build_tile_plan,
        coupling_expand,
        coupling_reduce,
        device_plan,
    )

    rng = np.random.default_rng(1)
    n, d, od, nseg = 8192, 9, 2, 57
    seg_of = np.sort(rng.integers(0, nseg, n)).astype(np.int32)
    plan = build_tile_plan(seg_of, nseg, tile=512, block=64)
    dp = device_plan(plan)

    J = (rng.standard_normal((od * d, plan.n_slots)) *
         plan.mask).astype(np.float32)
    table = rng.standard_normal((d, nseg)).astype(np.float32)
    u = (rng.standard_normal((od, plan.n_slots)) *
         plan.mask).astype(np.float32)

    J64 = J.astype(np.float64)
    seg = plan.seg

    got_u = np.asarray(coupling_expand(
        jnp.asarray(table), jnp.asarray(J), dp, d, use_kernels=True))
    ref_u = np.zeros((od, plan.n_slots))
    for o in range(od):
        for a in range(d):
            ref_u[o] += J64[o * d + a] * table.astype(np.float64)[a, seg]
    scale = max(np.abs(ref_u).max(), 1e-30)
    assert np.abs(got_u - ref_u).max() < 1e-4 * scale

    got_out = np.asarray(coupling_reduce(
        jnp.asarray(J), jnp.asarray(u), dp, d, use_kernels=True))
    ref_out = np.zeros((d, nseg))
    u64 = u.astype(np.float64)
    for b in range(d):
        row = sum(J64[o * d + b] * u64[o] for o in range(od))
        np.add.at(ref_out[b], seg, row)
    scale = max(np.abs(ref_out).max(), 1e-30)
    assert np.abs(got_out - ref_out).max() < 1e-4 * scale


def test_pgo_solve_on_chip(tpu_backend):
    # The second solver family end-to-end on hardware: a small loop-
    # closed pose graph converges (standalone; no CPU cross-check here
    # to keep chip time minimal).
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    g = make_synthetic_pose_graph(num_poses=48, loop_closures=10, seed=5)
    option = ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=12, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=40, tol=1e-10,
                                   refuse_ratio=1e30))
    res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)
    assert np.isfinite(float(res.cost))
    assert float(res.cost) < 0.05 * float(res.initial_cost)
    assert int(res.accepted) > 0
