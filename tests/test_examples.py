"""Every example entry point runs end to end on tiny synthetic scenes.

The example CLIs are the reference's de-facto integration tests
(SURVEY.md §4); a demo drifting out of sync with an internal API change
must fail CI, not a user.  Each runs as a real subprocess (the actual
CLI surface, argv parsing and __main__ included) with the CPU-pinned
environment — in-process imports were observed to push the suite's
single XLA process into a compiler segfault at full-suite compile
volume, and a subprocess per example isolates global jax state anyway.
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _run(script, args, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # The conftest's 8-virtual-device XLA_FLAGS must not leak into the
    # subprocess: a real CLI invocation has no such topology (and the
    # per-device thread pools cost on the 1-core sandbox).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, cwd=_ROOT,
        env=env)
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}")
    return proc.stdout


def _final_cost(out, marker):
    """Extract the final cost from the line containing `marker` and
    assert it is finite — 'cost nan' must fail, not pass on the marker
    alone."""
    line = next(ln for ln in out.splitlines() if marker in ln)
    floats = re.findall(r"-?(?:\d+\.?\d*|nan|inf)(?:e[+-]?\d+)?", line,
                        re.IGNORECASE)
    costs = [float(x) for x in floats]
    assert costs and all(np.isfinite(c) for c in costs), line
    return costs


_TINY_BAL = ["--max_iter", "2", "--synthetic_cameras", "4",
             "--synthetic_points", "40", "--synthetic_obs_per_point", "3"]


@pytest.mark.parametrize("name", [
    "BAL_Double", "BAL_Float", "BAL_Double_analytical",
    "BAL_Float_analytical", "BAL_Double_implicit",
    "BAL_Double_analytical_implicit",
])
def test_bal_examples_run(name):
    out = _run(f"{name}.py", _TINY_BAL)
    _final_cost(out, "Finished")


def test_planar_demo_runs():
    out = _run("planar_demo.py", ["--num_cameras", "4", "--num_points",
                                  "30", "--obs_per_point", "3",
                                  "--max_iter", "3"])
    _final_cost(out, "planar BA: cost")


def test_pgo_demo_runs():
    out = _run("pgo_demo.py", ["--num_poses", "10", "--loop_closures",
                               "2", "--max_iter", "5"])
    _final_cost(out, "PGO: cost")


def test_pgo_g2o_example_runs(tmp_path):
    out_path = str(tmp_path / "solved.g2o")
    out = _run("PGO_g2o.py", ["--synthetic_poses", "10",
                              "--synthetic_loop_closures", "2",
                              "--max_iter", "5", "--out", out_path])
    _final_cost(out, "PGO: cost")
    assert os.path.exists(out_path)
