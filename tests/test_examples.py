"""Every example entry point runs end to end on tiny synthetic scenes.

The example CLIs are the reference's de-facto integration tests
(SURVEY.md §4); a demo drifting out of sync with an internal API change
must fail CI, not a user.  Each runs in-process with tiny shapes so the
whole module stays in the quick lane.
"""

import importlib
import os

import numpy as np
import pytest


def _tiny_bal_argv():
    return ["--max_iter", "2", "--synthetic_cameras", "4",
            "--synthetic_points", "40", "--synthetic_obs_per_point", "3"]


@pytest.mark.parametrize("name", [
    "BAL_Double", "BAL_Float", "BAL_Double_analytical",
    "BAL_Float_analytical", "BAL_Double_implicit",
    "BAL_Double_analytical_implicit",
])
def test_bal_examples_run(name):
    mod = importlib.import_module(f"examples.{name}")
    cost = mod.main(_tiny_bal_argv())
    assert np.isfinite(cost)


def test_planar_demo_runs():
    planar_demo = importlib.import_module("examples.planar_demo")
    cost = planar_demo.main(num_cameras=4, num_points=30, obs_per_point=3,
                            max_iter=3)
    assert np.isfinite(cost)


def test_pgo_demo_runs():
    pgo_demo = importlib.import_module("examples.pgo_demo")
    cost = pgo_demo.main(["--num_poses", "10", "--loop_closures", "2",
                          "--max_iter", "5"])
    assert np.isfinite(cost)


def test_pgo_g2o_example_runs(tmp_path):
    PGO_g2o = importlib.import_module("examples.PGO_g2o")
    out = str(tmp_path / "solved.g2o")
    cost = PGO_g2o.main(["--synthetic_poses", "10",
                         "--synthetic_loop_closures", "2",
                         "--max_iter", "5", "--out", out])
    assert np.isfinite(cost)
    assert os.path.exists(out)
