"""Pre-flight triage: health checks, repairs, admission control.

Covers megba_tpu/robustness/triage.py and its integrations:

- unit checks/repairs are pure host NumPy (compile-free tests);
- REJECT dispatches NOTHING (retrace sentinel sees no traces, the
  PhaseTimer records a triage phase and no dispatch phase);
- the shift-left regression: a seeded deg-1-point problem solved
  UN-triaged fires runtime `precond_fallback` events; the SAME problem
  under TriagePolicy(REPAIR) solves clean with zero fallback events and
  a final cost within rtol 1e-6 of a hand-repaired control;
- the serving ingestion gate: duplicate edges / non-finite values are
  refused at `solve_many` / `FleetQueue.submit` (the PR 5 parser
  `_validate`, now shared) — the adversarial regression for data that
  used to sneak in through make_fleet / pad_to_class unchecked.
"""

import dataclasses

import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    JacobianMode,
    PreconditionerKind,
    ProblemOption,
    RobustOption,
    SolverOption,
    SolveStatus,
)
from megba_tpu.io.synthetic import make_synthetic_bal, project_batch_depth
from megba_tpu.robustness.triage import (
    CheckKind,
    HealthReport,
    ProblemRejected,
    TriageAction,
    TriagePolicy,
    check_problem,
    connected_components,
    huber_weight,
    triage_problem,
)

F32 = np.float32


def _clean(seed=0, **kw):
    return make_synthetic_bal(num_cameras=6, num_points=48, obs_per_point=3,
                              seed=seed, dtype=np.float64, **kw)


def _triage_args(s):
    return (s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx)


# ---------------------------------------------------------------------------
# checks (compile-free)
# ---------------------------------------------------------------------------


def test_clean_problem_is_warn_noop():
    s = _clean()
    out = triage_problem(*_triage_args(s))
    assert out.action == TriageAction.WARN
    assert out.repair is None
    assert not out.report.degenerate
    assert out.report.findings == []
    assert out.report.n_components == 1
    # Clean stays clean under every action policy.
    for act in TriageAction:
        out2 = triage_problem(*_triage_args(s),
                              TriagePolicy(on_degenerate=act))
        assert out2.repair is None and not out2.report.degenerate


def test_connected_components_toy_graphs():
    # one component
    n, cc, pc = connected_components(
        np.array([0, 1, 1]), np.array([0, 0, 1]), 2, 2)
    assert n == 1 and set(cc) == {0} and set(pc) == {0}
    # two components + an isolated point singleton
    n, cc, pc = connected_components(
        np.array([0, 1]), np.array([0, 1]), 2, 3)
    assert n == 3  # {c0,p0}, {c1,p1}, {p2}
    assert cc[0] != cc[1]
    assert pc[2] not in (pc[0], pc[1])
    # long chain exercises the path-halving loop
    k = 50
    ci = np.repeat(np.arange(k), 2)[1:-1]
    pi = np.repeat(np.arange(k - 1), 2)
    n, cc, pc = connected_components(ci, pi, k, k - 1)
    assert n == 1
    # masked edges split the graph
    n, cc, pc = connected_components(
        np.array([0, 1, 1]), np.array([0, 0, 1]), 2, 2,
        edge_alive=np.array([True, False, True]))
    assert n == 2 and cc[0] != cc[1]


def test_degree_checks_and_orphans():
    s = _clean()
    # append: a deg-1 point, a deg-0 point, an edge-less camera
    pts = np.concatenate([s.points0, [[0.1, 0.2, 0.3], [0.3, 0.1, 0.2]]])
    cams = np.concatenate([s.cameras0, s.cameras0[:1]])
    np_pt = s.points0.shape[0]
    ci = np.concatenate([s.cam_idx, [0]]).astype(np.int32)
    pi = np.concatenate([s.pt_idx, [np_pt]]).astype(np.int32)
    obs = np.concatenate([s.obs, [[0.0, 0.0]]])
    rep, internals = check_problem(cams, pts, obs, ci, pi,
                                   TriagePolicy(geometric=False))
    counts = rep.counts()
    assert counts["under_constrained_point"] == 2  # deg-1 AND deg-0
    assert counts["orphan_camera"] == 1
    f = rep.finding(CheckKind.UNDER_CONSTRAINED_POINT)
    assert set(f.exemplars) == {np_pt, np_pt + 1}
    assert internals["bad_pt"][np_pt] and internals["bad_pt"][np_pt + 1]
    assert rep.degenerate  # deg<2 points predict a singular Hll


def test_under_constrained_camera_is_advisory():
    # 3 cameras, 5 points; cameras 0/1 see all five (deg 5 = the
    # default floor), camera 2 sees a single point -> 2 residual rows
    # vs 9 dof.  Advisory: flagged, but NOT degenerate on its own.
    cams = np.zeros((3, 9))
    cams[:, 5] = -5.0
    cams[:, 6] = 500.0
    pts = np.array([[0.0, 0.0, 0.0], [0.1, 0.0, 0.0], [0.0, 0.1, 0.0],
                    [-0.1, 0.1, 0.0], [0.1, -0.1, 0.0]])
    ci = np.array([0] * 5 + [1] * 5 + [2], np.int32)
    pi = np.array(list(range(5)) * 2 + [0], np.int32)
    uv, _ = project_batch_depth(cams[ci], pts[pi])
    rep, _ = check_problem(cams, pts, uv, ci, pi,
                           TriagePolicy(geometric=False))
    counts = rep.counts()
    f = rep.finding(CheckKind.UNDER_CONSTRAINED_CAMERA)
    assert counts.get("under_constrained_camera") == 1
    assert f.exemplars == [2]
    assert not rep.degenerate


def test_duplicate_edges_first_occurrence_survives():
    s = _clean()
    ci = np.concatenate([s.cam_idx, s.cam_idx[5:6]]).astype(np.int32)
    pi = np.concatenate([s.pt_idx, s.pt_idx[5:6]]).astype(np.int32)
    obs = np.concatenate([s.obs, s.obs[5:6] + 1.0])
    out = triage_problem(s.cameras0, s.points0, obs, ci, pi,
                         TriagePolicy(on_degenerate=TriageAction.REPAIR))
    f = out.report.finding(CheckKind.DUPLICATE_EDGE)
    assert f is not None and f.count == 1
    em = out.repair.edge_mask
    assert em is not None
    assert em[len(s.cam_idx)] == 0.0  # the APPENDED copy is masked
    assert em[5] == 1.0  # the first occurrence survives


def test_nonfinite_findings_sanitised_and_masked():
    s = _clean()
    cams = s.cameras0.copy()
    cams[2, 4] = np.inf
    pts = s.points0.copy()
    pts[7] = np.nan
    obs = s.obs.copy()
    obs[11, 0] = np.nan
    out = triage_problem(cams, pts, obs, s.cam_idx, s.pt_idx,
                         TriagePolicy(on_degenerate=TriageAction.REPAIR))
    counts = out.report.counts()
    assert counts["nonfinite_camera"] == 1
    assert counts["nonfinite_point"] == 1
    assert counts["nonfinite_obs"] == 1
    rep = out.repair
    # Frozen blocks + masked edges + SANITISED values (the mask
    # multiplies residuals; 0 * NaN is NaN, so scrubbing is load-bearing)
    assert rep.cam_fixed[2] and rep.pt_fixed[7]
    assert np.isfinite(rep.cameras).all()
    assert np.isfinite(rep.points).all()
    assert np.isfinite(rep.obs).all()
    dead = (s.cam_idx == 2) | (s.pt_idx == 7)
    dead[11] = True
    assert (rep.edge_mask[dead] == 0.0).all()
    # untouched data is never rewritten
    keep = ~np.isnan(pts).any(axis=1)
    assert rep.points[keep].tobytes() == pts[keep].tobytes()


def test_freeze_only_repair_is_not_a_noop():
    """A repair whose ONLY effect is freezing/sanitising a zero-degree
    non-finite camera has no masked edges or anchors — it must still be
    applied (the NaN params would otherwise dispatch unscrubbed)."""
    s = _clean()
    cams = np.concatenate([s.cameras0, np.full((1, 9), np.nan)])
    out = triage_problem(cams, s.points0, s.obs, s.cam_idx, s.pt_idx,
                         TriagePolicy(on_degenerate=TriageAction.REPAIR))
    rep = out.repair
    assert rep is not None and not rep.is_noop
    assert rep.edges_masked == 0 and rep.cams_anchored == 0
    assert rep.cams_fixed == 1 and rep.cam_fixed[-1]
    assert np.isfinite(rep.cameras).all()
    # ...and the integration point applies it: flat_solve sanitises
    from megba_tpu.serving import FleetProblem, FleetQueue, FleetStats

    stats = FleetStats()
    option = ProblemOption(dtype=np.float64,
                           algo_option=AlgoOption(max_iter=2))
    p = FleetProblem(cameras=cams, points=s.points0, obs=s.obs,
                     cam_idx=s.cam_idx, pt_idx=s.pt_idx, name="nan-cam")
    with FleetQueue(option, max_wait_s=10.0, stats=stats) as q:
        fut = q.submit(p, triage=TriagePolicy(
            on_degenerate=TriageAction.REPAIR))
        q.flush()
        r = fut.result(timeout=120)
    assert stats.triage_repaired == 1
    assert np.isfinite(float(r.cost))
    assert np.isfinite(r.cameras).all()


def test_behind_camera_knob_and_check():
    s = _clean(n_behind_camera=2)
    rep, internals = check_problem(*_triage_args(s))
    f = rep.finding(CheckKind.BEHIND_CAMERA)
    assert f is not None and f.count == 4  # 2 points x 2 observing cams
    # the flagged edges' depths really are behind (z >= 0)
    uv, z = project_batch_depth(s.cameras0[s.cam_idx], s.points0[s.pt_idx])
    flagged = np.zeros(len(s.cam_idx), bool)
    flagged[np.nonzero(internals["bad_edge"])[0]] = True
    assert (z[flagged] >= -TriagePolicy().min_depth).all()
    # composition: masking both edges drops the points to deg 0
    assert rep.counts()["under_constrained_point"] == 2


def test_orphan_knob_deg1_and_far_initial_estimate():
    s = _clean(n_orphan_points=5)
    deg = np.bincount(s.pt_idx, minlength=s.points0.shape[0])
    orphans = np.nonzero(deg == 1)[0]
    assert orphans.size == 5
    # failed-triangulation model: initial estimate far out along the ray
    assert (np.linalg.norm(s.points0[orphans], axis=1) > 50).all()
    rep, _ = check_problem(*_triage_args(s))
    assert rep.counts()["under_constrained_point"] == 5
    # the far placement stays ON the observed ray: no extreme-residual
    # or cheirality finding rides along
    assert rep.finding(CheckKind.EXTREME_RESIDUAL) is None
    assert rep.finding(CheckKind.BEHIND_CAMERA) is None


def test_disconnect_knob_components_and_anchor():
    s = _clean(n_disconnect=2)
    out = triage_problem(*_triage_args(s),
                         TriagePolicy(on_degenerate=TriageAction.REPAIR))
    f = out.report.finding(CheckKind.DISCONNECTED)
    assert f is not None and f.count == 1  # one EXTRA camera component
    rep = out.repair
    assert rep.cams_anchored == 1
    # the anchor lands in the island (cameras 6..7), not the main rig
    assert rep.cam_fixed is not None
    assert np.nonzero(rep.cam_fixed)[0].min() >= 6
    # clean problems never anchor
    out2 = triage_problem(*_triage_args(_clean()),
                          TriagePolicy(on_degenerate=TriageAction.REPAIR))
    assert out2.repair is None


def test_extreme_residual_downweight_matches_robust_kernel():
    s = _clean()
    obs = s.obs.copy()
    obs[3] += 1e6  # gross outlier, still finite
    pol = TriagePolicy(on_degenerate=TriageAction.REPAIR,
                       max_residual_px=1e3)
    out = triage_problem(s.cameras0, s.points0, obs, s.cam_idx, s.pt_idx,
                         pol)
    f = out.report.finding(CheckKind.EXTREME_RESIDUAL)
    assert f is not None and f.count >= 1 and 3 in f.exemplars
    rep = out.repair
    assert rep.edges_downweighted >= 1
    em = rep.edge_mask
    assert 0.0 < em[3] < 1.0
    # the mask weight IS the solver's own Huber kernel: mask = sqrt(w),
    # with w = rho'(s) from ops/robust.rho_and_weight at the initial
    # squared residual — the numpy twin must match the jnp kernel.
    uv, _ = project_batch_depth(s.cameras0[s.cam_idx[3:4]],
                                s.points0[s.pt_idx[3:4]])
    s2 = float(np.sum((uv[0] - obs[3]) ** 2))
    from megba_tpu.ops.robust import RobustKind, rho_and_weight

    _, w_kernel = rho_and_weight(np.float64(s2), RobustKind.HUBER,
                                 pol.max_residual_px)
    np.testing.assert_allclose(em[3] ** 2, huber_weight(
        np.asarray([s2]), pol.max_residual_px)[0], rtol=1e-12)
    np.testing.assert_allclose(em[3], float(w_kernel), rtol=1e-6)
    # downweight_outliers=False soft-deletes instead
    out2 = triage_problem(
        s.cameras0, s.points0, obs, s.cam_idx, s.pt_idx,
        TriagePolicy(on_degenerate=TriageAction.REPAIR,
                     max_residual_px=1e3, downweight_outliers=False))
    assert out2.repair.edge_mask[3] == 0.0


def test_low_parallax_frozen_but_edges_kept():
    # two cameras at the SAME center: every ray pair is parallel, all
    # points are zero-parallax; repair freezes the points but keeps the
    # edges (fixed-landmark treatment).
    cams = np.zeros((2, 9))
    cams[:, 5] = -5.0
    cams[:, 6] = 500.0
    pts = np.array([[0.0, 0.0, 0.0], [0.3, 0.1, 0.0], [0.1, 0.3, 0.0],
                    [-0.2, 0.1, 0.0]])
    ci = np.array([0, 1] * 4, np.int32)
    pi = np.repeat(np.arange(4), 2).astype(np.int32)
    uv, _ = project_batch_depth(cams[ci], pts[pi])
    out = triage_problem(cams, pts, uv, ci, pi,
                         TriagePolicy(on_degenerate=TriageAction.REPAIR))
    f = out.report.finding(CheckKind.LOW_PARALLAX)
    assert f is not None and f.count == 4
    rep = out.repair
    assert rep.pt_fixed.all()
    assert rep.points_fixed == 4
    # edges KEPT: no mask entry dropped to zero for low parallax
    assert rep.edge_mask is None or (rep.edge_mask > 0).all()


def test_checks_honor_caller_operands():
    """Triage sees the graph the SOLVER will see: caller-masked edges
    don't count toward degrees, caller-fixed points are never
    under-constrained, and a component holding a caller-fixed camera is
    already anchored."""
    s = _clean()
    # masking one of a deg-2 point's edges makes it deg-1 HERE
    p0 = int(s.pt_idx[0])
    edges_p0 = np.nonzero(s.pt_idx == p0)[0]
    assert edges_p0.size >= 2
    em = np.ones(len(s.cam_idx))
    em[edges_p0[1:]] = 0.0  # leave exactly one alive observation
    rep, _ = check_problem(*_triage_args(s), TriagePolicy(), edge_mask=em)
    f = rep.finding(CheckKind.UNDER_CONSTRAINED_POINT)
    assert f is not None and p0 in f.exemplars
    # ...unless the caller already FIXED that point (identity Hll)
    ptf = np.zeros(s.points0.shape[0], bool)
    ptf[p0] = True
    rep2, _ = check_problem(*_triage_args(s), TriagePolicy(),
                            edge_mask=em, pt_fixed=ptf)
    assert rep2.finding(CheckKind.UNDER_CONSTRAINED_POINT) is None
    # a deg-1 knob problem whose orphans are pre-fixed is clean too
    s2 = _clean(n_orphan_points=3)
    deg = np.bincount(s2.pt_idx, minlength=s2.points0.shape[0])
    out = triage_problem(*_triage_args(s2), TriagePolicy(),
                         pt_fixed=deg < 2,
                         edge_mask=np.where((deg < 2)[s2.pt_idx], 0.0, 1.0))
    assert not out.report.degenerate
    # caller-masked duplicate copies don't double-count
    ci = np.concatenate([s.cam_idx, s.cam_idx[:1]]).astype(np.int32)
    pi = np.concatenate([s.pt_idx, s.pt_idx[:1]]).astype(np.int32)
    obs = np.concatenate([s.obs, s.obs[:1]])
    em2 = np.ones(len(ci))
    em2[-1] = 0.0
    rep3, _ = check_problem(s.cameras0, s.points0, obs, ci, pi,
                            TriagePolicy(), edge_mask=em2)
    assert rep3.finding(CheckKind.DUPLICATE_EDGE) is None


def test_anchored_component_needs_no_anchor():
    s = _clean(n_disconnect=2)
    n_cam = s.cameras0.shape[0]
    # fix one ISLAND camera (cameras 6..7): the island is anchored, so
    # the MAIN component is now the one needing a gauge (g2o semantics:
    # with any anchor present, every unanchored component gets one).
    cf = np.zeros(n_cam, bool)
    cf[6] = True
    out = triage_problem(*_triage_args(s),
                         TriagePolicy(on_degenerate=TriageAction.REPAIR),
                         cam_fixed=cf)
    f = out.report.finding(CheckKind.DISCONNECTED)
    assert f is not None and f.count == 1
    assert out.repair.cams_anchored == 1
    anchors = np.nonzero(out.repair.cam_fixed & ~cf)[0]
    assert anchors.size == 1 and anchors[0] < 6  # lands in the MAIN rig
    # fixing a camera in EVERY component: nothing to flag
    cf2 = np.zeros(n_cam, bool)
    cf2[0] = cf2[6] = True
    rep2, _ = check_problem(*_triage_args(s), TriagePolicy(),
                            cam_fixed=cf2)
    assert rep2.finding(CheckKind.DISCONNECTED) is None


def test_structural_false_still_hits_ingestion_gate():
    """TriagePolicy(structural=False) never scans for duplicates, so the
    shared parser gate must still refuse them at the serving boundary."""
    from megba_tpu.serving import FleetProblem, FleetQueue, solve_many

    s = _clean()
    option = ProblemOption(dtype=np.float64,
                           algo_option=AlgoOption(max_iter=2))
    dup = FleetProblem(
        cameras=s.cameras0, points=s.points0,
        obs=np.concatenate([s.obs, s.obs[:1]]),
        cam_idx=np.concatenate([s.cam_idx, s.cam_idx[:1]]),
        pt_idx=np.concatenate([s.pt_idx, s.pt_idx[:1]]),
        name="dup")
    pol = TriagePolicy(on_degenerate=TriageAction.REPAIR, structural=False)
    with FleetQueue(option, max_wait_s=10.0) as q:
        with pytest.raises(ValueError, match="duplicate observation"):
            q.submit(dup, triage=pol)
    # solve_many: a hand-attached health dict without a structural pass
    # does not bypass the gate either
    out = triage_problem(*_triage_args(s), pol)
    tagged = dataclasses.replace(dup, health=out.report.to_dict())
    assert tagged.health["structural"] is False
    with pytest.raises(ValueError, match="duplicate observation"):
        solve_many([tagged], option)


def test_policy_validation():
    with pytest.raises(ValueError):
        TriagePolicy(min_point_degree=0)
    with pytest.raises(ValueError):
        TriagePolicy(max_residual_px=0.0)
    with pytest.raises(ValueError):
        TriagePolicy(min_depth=-1.0)
    with pytest.raises(ValueError):
        TriagePolicy(exemplar_cap=0)
    with pytest.raises(ValueError):
        make_synthetic_bal(num_cameras=4, num_points=8, n_orphan_points=-1)


def test_report_roundtrip_and_rejection_payload():
    s = _clean(n_orphan_points=3)
    with pytest.raises(ProblemRejected) as ei:
        triage_problem(*_triage_args(s))
    rep = ei.value.report
    assert rep.degenerate and rep.action == "reject"
    assert "under_constrained_point" in str(ei.value)
    d = rep.to_dict()
    back = HealthReport.from_dict(d)
    assert back.to_dict() == d
    assert back.counts() == rep.counts()
    # exemplars are BOUNDED
    s2 = _clean(n_orphan_points=30)
    rep2, _ = check_problem(*_triage_args(s2),
                            TriagePolicy(exemplar_cap=4))
    f = rep2.finding(CheckKind.UNDER_CONSTRAINED_POINT)
    assert f.count == 30 and len(f.exemplars) == 4


def test_synthetic_knobs_zero_is_byte_identical():
    a = make_synthetic_bal(num_cameras=5, num_points=32, obs_per_point=2.5,
                           seed=11)
    b = make_synthetic_bal(num_cameras=5, num_points=32, obs_per_point=2.5,
                           seed=11, n_orphan_points=0, n_behind_camera=0,
                           n_disconnect=0)
    for f in ("cameras_gt", "points_gt", "cameras0", "points0", "obs",
              "cam_idx", "pt_idx"):
        assert getattr(a, f).tobytes() == getattr(b, f).tobytes(), f


def test_synthetic_knobs_still_cam_sorted_and_validated():
    s = _clean(n_orphan_points=2, n_behind_camera=2, n_disconnect=2, seed=4)
    assert (np.diff(s.cam_idx) >= 0).all()
    # the generator's own ingestion gate passed (no duplicates, finite)
    from megba_tpu.io.bal import validate_problem

    validate_problem(s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                     where="test")


# ---------------------------------------------------------------------------
# zero-dispatch REJECT + integration (compile-free)
# ---------------------------------------------------------------------------


def test_flat_solve_reject_zero_dispatch():
    from megba_tpu.analysis import retrace
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve
    from megba_tpu.utils.timing import PhaseTimer

    s = _clean(n_orphan_points=4)
    option = ProblemOption(dtype=F32, algo_option=AlgoOption(max_iter=4))
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    base = retrace.snapshot()
    timer = PhaseTimer()
    with pytest.raises(ProblemRejected) as ei:
        flat_solve(f, s.cameras0.astype(F32), s.points0.astype(F32),
                   s.obs.astype(F32), s.cam_idx, s.pt_idx, option,
                   use_tiled=False, timer=timer, triage=TriagePolicy())
    # ZERO device dispatch: no program traced, no dispatch/lowering
    # phase — the triage phase is the only thing the timer saw.
    assert retrace.snapshot() == base
    assert "dispatch" not in timer.totals
    assert "lowering" not in timer.totals
    assert "triage" in timer.totals
    assert ei.value.report.counts()["under_constrained_point"] == 4


def test_queue_triage_reject_resolves_future_fast():
    from megba_tpu.serving import FleetProblem, FleetQueue, FleetStats

    s = _clean(n_orphan_points=3)
    stats = FleetStats()
    option = ProblemOption(dtype=np.float64,
                           algo_option=AlgoOption(max_iter=2))
    with FleetQueue(option, max_batch=4, max_wait_s=10.0,
                    stats=stats) as q:
        fut = q.submit(FleetProblem.from_synthetic(s, name="deg"),
                       triage=TriagePolicy())
        # resolved IMMEDIATELY on the submitter's thread: never queued,
        # never dispatched, never in the escalation ladder
        assert fut.done()
        with pytest.raises(ProblemRejected):
            fut.result()
    assert stats.triage_rejected == 1
    assert stats.problems == 0 and stats.batches == 0  # nothing dispatched
    d = stats.as_dict()
    assert d["triage_rejected"] == 1


def test_serving_ingestion_gate_adversarial():
    """The PR 5 parser gate, now shared: duplicate edges and non-finite
    values are refused at BOTH serving boundaries (they used to sneak in
    through make_fleet / pad_to_class unchecked)."""
    from megba_tpu.serving import FleetProblem, FleetQueue, solve_many

    s = _clean()
    option = ProblemOption(dtype=np.float64,
                           algo_option=AlgoOption(max_iter=2))
    dup = FleetProblem(
        cameras=s.cameras0, points=s.points0,
        obs=np.concatenate([s.obs, s.obs[:1]]),
        cam_idx=np.concatenate([s.cam_idx, s.cam_idx[:1]]),
        pt_idx=np.concatenate([s.pt_idx, s.pt_idx[:1]]),
        name="dup")
    bad_obs = FleetProblem(
        cameras=s.cameras0, points=s.points0,
        obs=np.where(np.arange(s.obs.shape[0])[:, None] == 3,
                     np.nan, s.obs),
        cam_idx=s.cam_idx, pt_idx=s.pt_idx, name="nan")
    oob = FleetProblem(
        cameras=s.cameras0, points=s.points0, obs=s.obs,
        cam_idx=np.where(np.arange(s.cam_idx.shape[0]) == 0,
                         99, s.cam_idx).astype(np.int32),
        pt_idx=s.pt_idx, name="oob")
    for bad, what in ((dup, "duplicate"), (bad_obs, "non-finite"),
                      (oob, "out of range")):
        with pytest.raises(ValueError, match="BAL semantic error"):
            solve_many([bad], option)
        with FleetQueue(option, max_wait_s=10.0) as q:
            with pytest.raises(ValueError, match="BAL semantic error"):
                q.submit(bad)
    # triage REPAIR turns the duplicate-edge reject into a masked solve
    # (content admission repairs what the plain gate refuses) — pure
    # host decision, queue drained empty without dispatching anything.
    with FleetQueue(option, max_wait_s=10.0) as q:
        fut = q.submit(
            dup, triage=TriagePolicy(on_degenerate=TriageAction.REJECT))
        assert fut.done()


def test_aggregate_cli_renders_triage_counters(tmp_path):
    """Compile-free aggregate rendering over hand-built report lines."""
    import json

    from megba_tpu.observability import summarize
    from megba_tpu.observability.report import SolveReport

    s = _clean(n_orphan_points=2)
    out = triage_problem(*_triage_args(s),
                         TriagePolicy(on_degenerate=TriageAction.REPAIR))
    health = out.report.to_dict()
    base = dict(problem={}, config={}, backend={}, phases={},
                result={"status_name": "converged"})
    lines = [
        SolveReport(**base, health=health, created_unix=1.0,
                    fleet={"bucket": "b", "latency_s": 0.1,
                           "stats": {"triage_rejected": 3}}).to_json(),
        SolveReport(**base, created_unix=2.0).to_json(),
    ]
    path = tmp_path / "reports.jsonl"
    path.write_text("\n".join(lines) + "\n")
    text = summarize.aggregate_paths([str(path)])
    assert "triage: 3 rejected / 1 repaired" in text, text
    assert "2 points fixed" in text and "2 edges masked" in text, text
    assert "under_constrained_point=2" in text, text
    # round-trips through from_json too
    rep = SolveReport.from_json(lines[0])
    assert rep.health == json.loads(json.dumps(health))


def test_triage_module_is_jit_free():
    """The hygiene gate: triage is pure host NumPy — it never imports
    jax and contributes no jit entries to the analysis callgraph."""
    import megba_tpu.robustness.triage as triage_mod

    src = open(triage_mod.__file__).read()
    assert "import jax" not in src
    from megba_tpu.analysis.callgraph import PackageIndex

    index = PackageIndex.build([triage_mod.__file__])
    entries = [q for q, fn in index.functions.items() if fn.is_entry]
    assert entries == [], f"triage exposes jit entries: {entries}"


# ---------------------------------------------------------------------------
# the shift-left regression (compiles two small programs)
# ---------------------------------------------------------------------------


def _shift_left_option():
    return ProblemOption(
        dtype=F32,
        algo_option=AlgoOption(max_iter=10),
        solver_option=SolverOption(
            max_iter=20, tol=1e-10,
            preconditioner=PreconditionerKind.SCHUR_DIAG),
        robust_option=RobustOption(guards=True))


def test_shift_left_repair_eliminates_runtime_fallbacks():
    """A deg-1-point problem solved UN-triaged fires the runtime
    precond_fallback path (the far points' near-singular Hll crushes the
    Schur diagonal; its Cholesky goes NaN and falls back per block);
    the SAME problem under TriagePolicy(REPAIR) solves with ZERO
    fallback/recovery events and matches a hand-repaired control at
    rtol 1e-6 — i.e. triage de-loads the reactive guard layer."""
    from megba_tpu.observability.report import _decode_fallback_totals
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = make_synthetic_bal(num_cameras=6, num_points=48, obs_per_point=3,
                           seed=3, dtype=F32, n_orphan_points=6)
    option = _shift_left_option()
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    args = (f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)

    untriaged = flat_solve(*args, use_tiled=False)
    fb_un = _decode_fallback_totals(untriaged.trace,
                                    int(untriaged.iterations))
    assert fb_un["block"] > 0, (
        "expected the un-triaged deg-1 problem to fire runtime "
        f"precond_fallback events, got {fb_un} "
        f"(recoveries={int(untriaged.recoveries)})")

    triaged = flat_solve(
        *args, use_tiled=False,
        triage=TriagePolicy(on_degenerate=TriageAction.REPAIR))
    fb_tr = _decode_fallback_totals(triaged.trace, int(triaged.iterations))
    assert fb_tr == {"block": 0, "coarse": 0}, fb_tr
    assert int(triaged.recoveries) == 0
    assert int(triaged.status) in (SolveStatus.CONVERGED,
                                   SolveStatus.MAX_ITER)
    assert np.isfinite(float(triaged.cost))

    # hand-repaired control: manually freeze deg-1 points + mask edges
    deg = np.bincount(s.pt_idx, minlength=s.points0.shape[0])
    ptf = deg < 2
    em = np.where(ptf[s.pt_idx], 0.0, 1.0)
    control = flat_solve(*args, use_tiled=False, edge_mask=em, pt_fixed=ptf)
    assert int(control.status) == int(triaged.status)
    np.testing.assert_allclose(float(triaged.cost), float(control.cost),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(triaged.cameras),
                               np.asarray(control.cameras),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(triaged.points)[~ptf],
                               np.asarray(control.points)[~ptf],
                               rtol=1e-6, atol=1e-7)


def test_warn_solves_like_untriaged_with_report(tmp_path):
    """WARN changes nothing about the solve (bitwise) — it only attaches
    the health report; rides the programs the shift-left test compiled."""
    from megba_tpu.observability.report import SolveReport
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = make_synthetic_bal(num_cameras=6, num_points=48, obs_per_point=3,
                           seed=3, dtype=F32, n_orphan_points=6)
    option = _shift_left_option()
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    args = (f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx)

    plain = flat_solve(*args, option, use_tiled=False)
    sink = tmp_path / "warn.jsonl"
    opt_t = dataclasses.replace(option, telemetry=str(sink))
    warned = flat_solve(
        *args, opt_t, use_tiled=False,
        triage=TriagePolicy(on_degenerate=TriageAction.WARN))
    assert np.asarray(warned.cameras).tobytes() == \
        np.asarray(plain.cameras).tobytes()
    assert float(warned.cost) == float(plain.cost)
    rep = SolveReport.from_json(sink.read_text().strip().splitlines()[-1])
    assert rep.health is not None
    assert rep.health["action"] == "warn"
    assert rep.health["degenerate"]
    assert rep.health["repair"] is None


@pytest.mark.slow
def test_queue_triage_repair_end_to_end_batched():
    """REPAIR through the fleet queue: the repaired problem rides the
    batched program as pure operands next to a clean batch-mate, whose
    result stays BITWISE identical to a solo solve_many control."""
    from megba_tpu.serving import (
        FleetProblem,
        FleetQueue,
        FleetStats,
        solve_many,
    )

    deg = make_synthetic_bal(num_cameras=6, num_points=48, obs_per_point=3,
                             seed=3, dtype=np.float64, n_orphan_points=6)
    clean = _clean(seed=9)
    option = ProblemOption(dtype=np.float64,
                           algo_option=AlgoOption(max_iter=5),
                           solver_option=SolverOption(max_iter=10, tol=1e-9))
    p_deg = FleetProblem.from_synthetic(deg, name="deg")
    p_clean = FleetProblem.from_synthetic(clean, name="clean")
    stats = FleetStats()
    with FleetQueue(option, max_batch=4, max_wait_s=30.0,
                    stats=stats) as q:
        f_deg = q.submit(
            p_deg, triage=TriagePolicy(on_degenerate=TriageAction.REPAIR))
        f_clean = q.submit(p_clean)
        q.flush()
        r_deg = f_deg.result(timeout=10)
        r_clean = f_clean.result(timeout=10)
    assert stats.triage_repaired == 1
    assert r_deg.health is not None
    assert r_deg.health["repair"]["points_fixed"] == 6
    assert np.isfinite(float(r_deg.cost))
    # Control: the SAME two-lane batch built by hand — triage repair
    # applied directly, then solve_many (lane count is part of the
    # compiled program, so the control must match the composition).
    out = triage_problem(
        deg.cameras0, deg.points0, deg.obs, deg.cam_idx, deg.pt_idx,
        TriagePolicy(on_degenerate=TriageAction.REPAIR))
    rep = out.repair
    p_repaired = dataclasses.replace(
        p_deg, edge_mask=rep.edge_mask, cam_fixed=rep.cam_fixed,
        pt_fixed=rep.pt_fixed, health=out.report.to_dict())
    ctrl_deg, ctrl_clean = solve_many([p_repaired, p_clean], option)
    assert r_clean.cameras.tobytes() == ctrl_clean.cameras.tobytes()
    assert r_clean.cost.tobytes() == ctrl_clean.cost.tobytes()
    assert r_deg.cameras.tobytes() == ctrl_deg.cameras.tobytes()
    assert r_deg.cost.tobytes() == ctrl_deg.cost.tobytes()
