"""Native C++ runtime tests: build, parse parity, index builders."""

import numpy as np
import pytest

from megba_tpu.io.bal import BALFile, load_bal, save_bal
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.native import (
    degree_stats,
    get_lib,
    parse_bal_native,
    partition_bounds,
    sort_edges_by_camera,
)


@pytest.fixture(scope="module")
def lib():
    lib = get_lib()
    if lib is None:
        pytest.skip("native library unavailable (g++ build failed)")
    return lib


def test_native_builds(lib):
    assert lib is not None


def test_native_parse_matches_python(lib, tmp_path):
    s = make_synthetic_bal(num_cameras=4, num_points=30, obs_per_point=3, seed=9)
    bal = BALFile(cameras=s.cameras0, points=s.points0, obs=s.obs,
                  cam_idx=s.cam_idx, pt_idx=s.pt_idx)
    p = str(tmp_path / "prob.txt")
    save_bal(p, bal)
    native = parse_bal_native(p)
    # Python fallback assembles via np.fromfile; both must agree exactly.
    with open(p, "rb") as f:
        tokens = np.fromfile(f, sep=" ")
    from megba_tpu.io.bal import _assemble
    py = _assemble(tokens, np.float64)
    np.testing.assert_array_equal(native.cam_idx, py.cam_idx)
    np.testing.assert_array_equal(native.pt_idx, py.pt_idx)
    np.testing.assert_array_equal(native.obs, py.obs)
    np.testing.assert_array_equal(native.cameras, py.cameras)
    np.testing.assert_array_equal(native.points, py.points)
    # And load_bal prefers the native path transparently.
    loaded = load_bal(p)
    np.testing.assert_array_equal(loaded.cameras, py.cameras)


def test_native_parse_rejects_truncated(lib, tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("2 2 3\n0 0 1.0 2.0\n")
    with pytest.raises(ValueError, match="parse failed"):
        parse_bal_native(str(p))


def test_sort_edges(lib):
    rng = np.random.default_rng(0)
    cam_idx = rng.integers(0, 50, size=1000).astype(np.int32)
    perm = sort_edges_by_camera(cam_idx, 50)
    expect = np.argsort(cam_idx, kind="stable")
    np.testing.assert_array_equal(perm, expect)


def test_degree_stats(lib):
    cam_idx = np.array([0, 0, 1, 2, 2, 2], np.int32)
    pt_idx = np.array([0, 1, 1, 0, 0, 2], np.int32)  # (2,0) repeated
    cam_counts, pt_counts, (max_c, max_p, nnz) = degree_stats(cam_idx, pt_idx, 3, 3)
    np.testing.assert_array_equal(cam_counts, [2, 1, 3])
    np.testing.assert_array_equal(pt_counts, [3, 2, 1])
    assert max_c == 3 and max_p == 3
    assert nnz == 5  # (0,0),(0,1),(1,1),(2,0),(2,2)


def test_degree_stats_unsorted_flags():
    cam_idx = np.array([1, 0], np.int32)
    pt_idx = np.array([0, 0], np.int32)
    _, _, (_, _, nnz) = degree_stats(cam_idx, pt_idx, 2, 1)
    assert nnz == -1


def test_partition_bounds(lib):
    b = partition_bounds(10, 4)
    np.testing.assert_array_equal(b, [0, 3, 6, 9, 12])
    b = partition_bounds(8, 4)
    np.testing.assert_array_equal(b, [0, 2, 4, 6, 8])
