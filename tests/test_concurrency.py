"""Concurrency contract lane: guarded-by / lock-order / blocking-under-lock.

Compile-free tier-1 units — every finding class the analyzer knows gets
a positive (fires on a handwritten fixture) AND a negative (silent on
the sanctioned variant), so a pass that silently stops matching — or
starts over-matching — breaks this suite rather than the serving tier.
The seeded lint fixtures are pinned to exact per-rule counts, and the
package itself must stay at zero findings.
"""

import os
import textwrap

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "lint_fixtures")
BAD = os.path.join(FIXTURES, "bad_concurrency.py")
GOOD = os.path.join(FIXTURES, "good_concurrency.py")
PACKAGE = os.path.join(os.path.dirname(__file__), "..", "megba_tpu")

CONCURRENCY_RULES = ["guarded-by", "lock-order", "blocking-under-lock"]


def _lint(*paths, rules=CONCURRENCY_RULES):
    from megba_tpu.analysis.lint import lint_paths

    return lint_paths(list(paths), rules=list(rules))


def _lint_source(tmp_path, source, rules=CONCURRENCY_RULES):
    """Write an inline fixture module and run the concurrency rules."""
    mod = tmp_path / "fixture_mod.py"
    mod.write_text(textwrap.dedent(source))
    return _lint(str(mod), rules=rules)


# ----------------------------------------------------------- guarded-by


def test_declared_guard_unlocked_write_and_read(tmp_path):
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # megba: guarded-by(_lock)

            def ok(self):
                with self._lock:
                    self.n += 1

            def racy_write(self):
                self.n += 1

            def racy_read(self):
                return self.n
        """)
    assert len(findings) == 2
    assert all(f.rule == "guarded-by" for f in findings)
    kinds = sorted(f.message.split()[0] for f in findings)
    assert kinds == ["read", "write"]
    assert all("self._lock" in f.message and "(declared)" in f.message
               for f in findings)


def test_declared_guard_enforced_without_thread_census(tmp_path):
    """Declarations are a contract: enforced even when the analyzer
    never sees a `threading.Thread` touch the class."""
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # megba: guarded-by(_lock)

            def racy(self):
                self.n += 1
        """)
    assert len(findings) == 1 and findings[0].rule == "guarded-by"


_INFERENCE_TEMPLATE = """\
    import threading

    class C:
        def __init__(self):
            self._mu = threading.Lock()
            self.total = 0
            {thread}

        def _work(self):
            with self._mu:
{bumps}

        def peek(self):
            return self.total
    """


def _inference_src(locked_bumps, threaded=True):
    thread = ("threading.Thread(target=self._work, daemon=True).start()"
              if threaded else "pass")
    bumps = "\n".join(" " * 16 + f"self.total += {i}"
                      for i in range(locked_bumps))
    return _INFERENCE_TEMPLATE.format(thread=thread, bumps=bumps)


def test_inference_fires_at_threshold(tmp_path):
    """5 locked accesses + 1 unlocked read = 5/6 >= 80% of >= 5: the
    guard is inferred and `peek` flags."""
    findings = _lint_source(tmp_path, _inference_src(5))
    assert len(findings) == 1
    assert findings[0].rule == "guarded-by"
    assert "inferred: 5/6" in findings[0].message


def test_inference_silent_below_access_floor(tmp_path):
    """4 locked + 1 unlocked = 5 accesses but 4/5 = 80% of only 4 under
    the lock... the floor is >= 5 *post-init* accesses under one lock is
    not required — the ratio drops to 80% exactly; shrink to 3 locked so
    3/4 < 80% stays silent."""
    findings = _lint_source(tmp_path, _inference_src(3))
    assert findings == []


def test_inference_silent_without_thread_census(tmp_path):
    """Same shape as the firing case, but no thread ever reaches the
    class: single-threaded objects need no guard."""
    findings = _lint_source(tmp_path, _inference_src(5, threaded=False))
    assert findings == []


def test_init_settled_field_is_silent(tmp_path):
    """Written only in __init__, read everywhere: safe publication."""
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.name = "x"
                threading.Thread(target=self.run, daemon=True).start()

            def run(self):
                with self._lock:
                    pass

            def label(self):
                return self.name
        """)
    assert findings == []


def test_allow_unguarded_pragma_suppresses(tmp_path):
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # megba: guarded-by(_lock)

            def gauge(self):
                return self.n  # megba: allow-unguarded
        """)
    assert findings == []


def test_declared_alias_lock_counts_as_owned(tmp_path):
    """A guard handed in from outside (not ctor-constructed) still IS
    the contract: `with self._lock` satisfies it, unlocked access
    flags."""
    findings = _lint_source(tmp_path, """\
        class C:
            def __init__(self, registry):
                self._lock = registry.lock
                self.n = 0  # megba: guarded-by(_lock)

            def ok(self):
                with self._lock:
                    self.n += 1

            def racy(self):
                self.n += 1
        """)
    assert len(findings) == 1
    assert findings[0].rule == "guarded-by"
    assert findings[0].line == 11


def test_entry_held_private_helper(tmp_path):
    """A private method called only under the lock inherits it at
    entry — no pragma, no finding."""
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # megba: guarded-by(_lock)
                threading.Thread(target=self.run, daemon=True).start()

            def run(self):
                with self._lock:
                    self._append_locked(1)

            def _append_locked(self, x):
                self.items.append(x)
        """)
    assert findings == []


# ----------------------------------------------------------- lock-order


def test_lock_order_cycle_with_witness_path(tmp_path):
    findings = _lint_source(tmp_path, """\
        import threading

        class D:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "lock-order"
    assert "witness path" in f.message
    # The witness names both locks and cites acquisition sites.
    assert "D._a" in f.message and "D._b" in f.message
    assert "acquire" in f.message


def test_lock_order_consistent_nesting_is_silent(tmp_path):
    findings = _lint_source(tmp_path, """\
        import threading

        class D:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert findings == []


def test_lock_order_cycle_through_callgraph(tmp_path):
    """The inversion spans two methods joined by a self-call: the
    acquires-while-holding edge must be computed transitively."""
    findings = _lint_source(tmp_path, """\
        import threading

        class D:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def outer(self):
                with self._a:
                    self.inner()

            def inner(self):
                with self._b:
                    pass

            def inverted(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert any(f.rule == "lock-order" for f in findings)


def test_condition_wait_reacquire_edge(tmp_path):
    """`Condition.wait` re-acquires its condition LAST: holding any
    other lock across the wait is an ordering edge held-lock -> cond,
    and here it is the ONLY source of the cycle."""
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()
                self._gate = threading.Lock()

            def step(self):
                with self._cond:
                    self._locked_step()

            def _locked_step(self):
                with self._gate:
                    self._cond.wait(0.01)
        """)
    cycles = [f for f in findings if f.rule == "lock-order"]
    assert len(cycles) == 1
    assert "Condition.wait re-acquire" in cycles[0].message


# -------------------------------------------------- blocking-under-lock


@pytest.mark.parametrize("call,label", [
    ("self._q.get()", "queue get"),
    ("worker.join()", "thread/queue join"),
    ("time.sleep(0.5)", "time.sleep(0.5)"),
    ("conn.recv(4096)", "conn.recv"),
    ("fut.result()", "Future.result"),
])
def test_blocking_call_under_lock_fires(tmp_path, call, label):
    findings = _lint_source(tmp_path, f"""\
        import queue
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def stall(self, worker, conn, fut):
                with self._lock:
                    return {call}
        """)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "blocking-under-lock"
    assert label in f.message
    assert "S._lock" in f.message


@pytest.mark.parametrize("call", [
    "self._d.get('k')",        # dict.get(key): an argument means lookup
    "', '.join(parts)",        # str.join, not thread join
    "time.sleep(0.01)",        # below the 0.05 s stall threshold
])
def test_non_blocking_lookalikes_stay_silent(tmp_path, call):
    findings = _lint_source(tmp_path, f"""\
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {{}}

            def fine(self, parts):
                with self._lock:
                    return {call}
        """)
    assert findings == []


def test_blocking_outside_lock_is_silent(tmp_path):
    findings = _lint_source(tmp_path, """\
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def fine(self, fut):
                with self._lock:
                    pass
                time.sleep(0.5)
                return fut.result()
        """)
    assert findings == []


def test_wait_on_held_condition_is_sanctioned(tmp_path):
    """Waiting on the condition you hold releases it — the canonical
    pattern must not flag."""
    findings = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False  # megba: guarded-by(_cond)

            def wait_ready(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait(0.1)
        """)
    assert findings == []


def test_module_lock_blocking_fires(tmp_path):
    """Module-level locks participate: blocking under one flags too."""
    findings = _lint_source(tmp_path, """\
        import threading

        _LOCK = threading.Lock()

        def fetch(fut):
            with _LOCK:
                return fut.result()
        """)
    assert len(findings) == 1
    assert findings[0].rule == "blocking-under-lock"
    assert "_LOCK" in findings[0].message


# ------------------------------------------------------ seeded fixtures


def test_bad_fixture_pinned_counts():
    """Pin exact per-rule hit counts in the seeded fixture, so both
    silent pass decay and over-matching regress loudly."""
    from collections import Counter

    counts = Counter(f.rule for f in _lint(BAD))
    assert counts == {
        "guarded-by": 3,           # racy write, racy read, inferred read
        "lock-order": 2,           # AB/BA cycle, Condition re-acquire
        "blocking-under-lock": 6,  # wait stall, Future.result, queue
                                   # get, thread join, long sleep, recv
    }


def test_bad_fixture_witness_path_details():
    cycles = [f for f in _lint(BAD, rules=["lock-order"])]
    assert len(cycles) == 2
    texts = sorted(f.message for f in cycles)
    assert "Condition.wait re-acquire" in texts[0]
    assert "Deadlock._a" in texts[1] and "Deadlock._b" in texts[1]


def test_good_fixture_is_silent():
    findings = _lint(GOOD)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_package_has_zero_findings():
    """THE acceptance gate: the serving tier itself carries no
    concurrency-contract violations."""
    findings = _lint(PACKAGE)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_no_allow_unguarded_pragmas_in_serving():
    """The escape hatch exists but the serving tier must not use it."""
    serving = os.path.join(PACKAGE, "serving")
    hits = []
    for name in sorted(os.listdir(serving)):
        if not name.endswith(".py"):
            continue
        path = os.path.join(serving, name)
        with open(path) as fh:
            for ln, line in enumerate(fh, 1):
                if "allow-unguarded" in line:
                    hits.append(f"{path}:{ln}")
    assert hits == []


# ------------------------------------------------------------------ CLI


@pytest.mark.parametrize("rule", CONCURRENCY_RULES)
def test_cli_exits_nonzero_per_rule(rule, capsys):
    from megba_tpu.analysis.lint import run_lint

    rc = run_lint(["--rule", rule, BAD])
    out = capsys.readouterr().out
    assert rc == 1
    assert rule in out


def test_cli_exits_zero_on_good(capsys):
    from megba_tpu.analysis.lint import run_lint

    rc = run_lint(["--rule", "guarded-by", "--rule", "lock-order",
                   "--rule", "blocking-under-lock", GOOD])
    capsys.readouterr()
    assert rc == 0
