"""Preemption safety, with a real process death.

The checkpointed drivers promise SIGKILL-anywhere safety: a solve killed
mid-chunk resumes from the last atomic snapshot and finishes BITWISE
identical — parameters AND stitched trace — to an uninterrupted run.
The kill here is a genuine SIGKILL delivered by the harness
(megba_tpu/robustness/harness.py) the moment the first snapshot lands,
i.e. while chunk 2 is computing: no atexit, no flush, no cleanup — the
preempted-host scenario.

The atomic-write half of the promise (crash BETWEEN temp-write and
rename) and the corrupt/truncated-snapshot rejections are covered
in-process below — they need fault simulation, not process death.
"""

import os

import numpy as np
import pytest

from megba_tpu.robustness.harness import (
    python_worker,
    run_to_completion,
    run_until_snapshot_then_kill,
)
from megba_tpu.utils.checkpoint import load_state, save_state

_WORKER = os.path.join(os.path.dirname(__file__), "_killresume_worker.py")


def _run_result(path):
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def test_sigkill_mid_chunk_resume_is_bitwise_identical(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # Reference: one uninterrupted run.
    ck_a = str(tmp_path / "a.npz")
    out_a = str(tmp_path / "a_result.npz")
    run_to_completion(python_worker(_WORKER, ck_a, out_a), env=env)

    # Interrupted run: SIGKILL as soon as the first snapshot exists
    # (chunk 2 is mid-flight), then resume to completion.
    ck_b = str(tmp_path / "b.npz")
    out_b = str(tmp_path / "b_result.npz")
    rc = run_until_snapshot_then_kill(
        python_worker(_WORKER, ck_b, out_b), ck_b, env=env)
    assert rc != 0  # killed, not exited
    assert not os.path.exists(out_b)  # died before finishing
    st = load_state(ck_b)  # the surviving snapshot is valid + complete
    assert int(st["iteration"]) >= 2
    run_to_completion(python_worker(_WORKER, ck_b, out_b), env=env)

    a, b = _run_result(out_a), _run_result(out_b)
    assert set(a) == set(b)
    for key in sorted(a):
        assert np.array_equal(a[key], b[key]), (
            f"{key} differs between uninterrupted and killed+resumed runs")


# ----------------------------------------------- atomic-write simulation


def test_crash_between_write_and_rename_preserves_old_snapshot(
        tmp_path, monkeypatch):
    path = str(tmp_path / "snap.npz")
    save_state(path, np.ones((2, 2)), np.zeros((3,)), region=1.5,
               iteration=4)
    before = load_state(path)

    real_replace = os.replace

    def exploding_replace(src, dst):
        raise OSError("simulated crash between write and rename")

    monkeypatch.setattr(os, "replace", exploding_replace)
    with pytest.raises(OSError, match="simulated crash"):
        save_state(path, np.full((2, 2), 9.0), np.ones((3,)), region=9.9,
                   iteration=5)
    monkeypatch.setattr(os, "replace", real_replace)

    # The old snapshot is intact and no temp files leaked beside it.
    after = load_state(path)
    for key in before:
        np.testing.assert_array_equal(before[key], after[key])
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_truncated_snapshot_raises_clear_error(tmp_path):
    path = str(tmp_path / "snap.npz")
    save_state(path, np.ones((4, 4)), np.zeros((5,)), region=2.0,
               iteration=1)
    raw = open(path, "rb").read()
    for frac in (0.1, 0.5, 0.9):
        open(path, "wb").write(raw[: int(len(raw) * frac)])
        with pytest.raises(ValueError, match="corrupt or truncated"):
            load_state(path)


def test_missing_snapshot_raises_file_not_found(tmp_path):
    """A path that simply does not exist is 'no snapshot yet', not
    corruption — callers probing for an optional snapshot must see the
    real FileNotFoundError, not a misleading 'corrupt or truncated'."""
    with pytest.raises(FileNotFoundError):
        load_state(str(tmp_path / "never_written.npz"))


def test_bitflip_snapshot_fails_checksum(tmp_path):
    path = str(tmp_path / "snap.npz")
    save_state(path, np.arange(64.0).reshape(8, 8), np.zeros((5,)),
               region=2.0, iteration=1)
    raw = bytearray(open(path, "rb").read())
    # Flip one byte inside the stored `cameras` payload (npz members are
    # uncompressed, so the float bytes appear literally; find a byte of
    # the value 7.0 = 0x401C000000000000 and flip it).
    needle = np.float64(7.0).tobytes()
    at = bytes(raw).find(needle)
    assert at > 0
    raw[at + 3] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    # Depending on where the flip lands this trips either the zip CRC
    # ("corrupt or truncated") or the content checksum ("snapshot is
    # corrupt") — both refuse with a clear "corrupt" error, never
    # garbage state.
    with pytest.raises(ValueError, match="corrupt"):
        load_state(path)


def test_checksum_mismatch_rejected_even_with_valid_zip(tmp_path):
    from megba_tpu.utils import checkpoint as ckpt

    path = str(tmp_path / "snap.npz")
    save_state(path, np.ones((2, 2)), np.zeros((3,)), region=1.0)
    with np.load(path) as z:
        st = {k: z[k] for k in z.files}
    st["cameras"] = st["cameras"] + 1.0  # tampered array, stale checksum
    np.savez(path, **st)  # valid zip, so only OUR checksum can catch it
    assert ckpt._CHECKSUM_KEY in st
    with pytest.raises(ValueError, match="checksum"):
        load_state(path)


def test_schema_version_checked(tmp_path):
    from megba_tpu.utils import checkpoint as ckpt

    path = str(tmp_path / "snap.npz")
    save_state(path, np.ones((2, 2)), np.zeros((3,)))
    st = load_state(path)  # internal keys are stripped from the payload
    assert not any(k.startswith("__") for k in st)
    # A snapshot from a NEWER schema is refused, not half-parsed.
    future = dict(st)
    future[ckpt._SCHEMA_KEY] = np.asarray(ckpt.SCHEMA_VERSION + 1)
    future[ckpt._CHECKSUM_KEY] = ckpt._digest(future)
    np.savez(path, **future)
    with pytest.raises(ValueError, match="newer schema"):
        load_state(path)


def test_legacy_checksum_free_snapshot_still_loads(tmp_path):
    """Snapshots written before the checksum existed (or round-tripped
    through external tooling) predate the guarantee — they load with a
    best-effort pass-through rather than being bricked."""
    path = str(tmp_path / "snap.npz")
    np.savez(path, cameras=np.ones((2, 2)), points=np.zeros((3,)),
             region=np.asarray(1.0))
    st = load_state(path)
    np.testing.assert_array_equal(st["cameras"], np.ones((2, 2)))
