"""Host-side (numpy) SE(3) helpers vs the device-side geo (jax) ops.

core/host_se3.py is the batched numpy math the IO/problem-construction
paths use; its charts must agree with ops/geo.py, which the solver
differentiates through.
"""

import numpy as np

import jax
import jax.numpy as jnp

from megba_tpu.core import host_se3
from megba_tpu.ops import geo


def _rand_poses(rng, n, scale=2.0):
    p = rng.standard_normal((n, 6))
    p[:, :3] *= scale  # rotation angles across both |aa| branches
    return p


def test_charts_match_geo():
    rng = np.random.default_rng(0)
    aa = np.concatenate([
        rng.standard_normal((40, 3)) * 2.0,
        rng.standard_normal((10, 3)) * 1e-9,  # small-angle branch
        np.zeros((1, 3)),
    ])
    q = host_se3.aa_to_quat(aa)
    # Unit norm and w >= 0 convention on the way back.
    np.testing.assert_allclose(np.linalg.norm(q, axis=-1), 1.0, rtol=1e-12)
    back = host_se3.quat_to_aa(q)
    # |aa| <= pi round-trips exactly; larger angles fold to the
    # principal branch — compare as rotations via geo.
    R1 = np.asarray(jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(aa)))
    R2 = np.asarray(jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(back)))
    np.testing.assert_allclose(R1, R2, atol=1e-7)
    # quat chart agrees with geo's quaternion_to_angle_axis (wxyz).
    q_wxyz = np.concatenate([q[:, 3:4], q[:, :3]], axis=1)
    ref = np.asarray(jax.vmap(geo.quaternion_to_angle_axis)(
        jnp.asarray(q_wxyz)))
    np.testing.assert_allclose(back, ref, atol=1e-6)


def test_compose_relative_consistency():
    rng = np.random.default_rng(1)
    a = _rand_poses(rng, 32)
    b = _rand_poses(rng, 32)
    ab = host_se3.compose(a, b)
    # relative(a, compose(a, b)) == b as SE(3) elements.
    rel = host_se3.relative(a, ab)
    Rb = np.asarray(jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(b[:, :3])))
    Rr = np.asarray(jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(rel[:, :3])))
    np.testing.assert_allclose(Rr, Rb, atol=1e-9)
    np.testing.assert_allclose(rel[:, 3:], b[:, 3:], atol=1e-9)
    # relative(a, b) agrees with the solver's between_residual zero:
    # between_residual(a, b, relative(a, b)) == 0.
    from megba_tpu.models.pgo import between_residual

    r = jax.vmap(between_residual)(
        jnp.asarray(a), jnp.asarray(b),
        jnp.asarray(host_se3.relative(a, b)))
    assert float(jnp.max(jnp.abs(r))) < 1e-9


def test_quat_rotate_matches_matrix():
    rng = np.random.default_rng(2)
    aa = rng.standard_normal((16, 3)) * 2.0
    v = rng.standard_normal((16, 3))
    R = np.asarray(jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(aa)))
    out = host_se3.quat_rotate(host_se3.aa_to_quat(aa), v)
    np.testing.assert_allclose(out, np.einsum("nij,nj->ni", R, v),
                               atol=1e-10)
