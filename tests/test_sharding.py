"""Multi-device tests on the virtual CPU mesh (SURVEY.md §4e).

The reference could never test its distribution without real GPUs; here
world_size 1/2/8 runs on 8 virtual CPU devices and must agree with the
single-device solve.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import cpu_devices

from megba_tpu.algo import lm_solve
from megba_tpu.common import AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.parallel import distributed_lm_solve, make_mesh, shard_edge_arrays


def make_problem(seed=0):
    return make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                              seed=seed, param_noise=4e-2, pixel_noise=0.3)


def make_option(compute_kind=ComputeKind.IMPLICIT):
    return ProblemOption(
        compute_kind=compute_kind,
        algo_option=AlgoOption(max_iter=12, epsilon1=1e-10, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=120, tol=1e-13, refuse_ratio=1e30),
    )


def solve_world(s, world_size, compute_kind=ComputeKind.IMPLICIT):
    option = make_option(compute_kind)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    obs, cam_idx, pt_idx, mask = shard_edge_arrays(
        s.obs, s.cam_idx, s.pt_idx, world_size)
    mesh = make_mesh(world_size, cpu_devices(world_size))
    return distributed_lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
        jnp.asarray(obs.T), jnp.asarray(cam_idx), jnp.asarray(pt_idx),
        jnp.asarray(mask), option, mesh)


@pytest.mark.parametrize("world_size", [2, 8])
@pytest.mark.parametrize("compute_kind", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_distributed_matches_single_device(world_size, compute_kind):
    s = make_problem()
    res1 = solve_world(s, 1, compute_kind)
    resn = solve_world(s, world_size, compute_kind)
    # Same algorithm, same partition semantics — only psum reduction order
    # differs, so float64 costs agree tightly.
    np.testing.assert_allclose(float(resn.cost), float(res1.cost), rtol=1e-9)
    np.testing.assert_allclose(float(resn.initial_cost), float(res1.initial_cost), rtol=1e-12)
    assert int(resn.iterations) == int(res1.iterations)
    # Parameters drift slightly along the BA gauge directions from psum
    # reduction-order differences; compare loosely (the strict invariant
    # is the cost above).
    np.testing.assert_allclose(np.asarray(resn.cameras), np.asarray(res1.cameras),
                               rtol=5e-3, atol=1e-4)


def test_distributed_mixed_precision():
    # The Jacobi scale-then-cast equilibration must stay consistent across
    # shards: d_cam/d_pt are computed from psum-reduced (replicated)
    # blocks, so every shard scales identically.
    import dataclasses
    s = make_problem(seed=5)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    option = dataclasses.replace(make_option(), mixed_precision_pcg=True)
    obs, cam_idx, pt_idx, mask = shard_edge_arrays(s.obs, s.cam_idx, s.pt_idx, 4)
    mesh = make_mesh(4, cpu_devices(4))
    res = distributed_lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
        jnp.asarray(obs.T), jnp.asarray(cam_idx), jnp.asarray(pt_idx),
        jnp.asarray(mask), option, mesh)
    single = distributed_lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
        jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx),
        jnp.ones(len(s.obs)), option, make_mesh(1, cpu_devices(1)))
    assert float(res.cost) < float(res.initial_cost) * 1e-2
    # bf16 rounding differs with shard count, so the LM trajectories
    # diverge slightly; both must land at the same basin.
    np.testing.assert_allclose(float(res.cost), float(single.cost), rtol=1e-2)


def test_jit_cache_reused():
    # Two same-shape solves must reuse the cached jitted program.
    from megba_tpu.parallel.mesh import _cached_sharded_solve
    _cached_sharded_solve.cache_clear()
    s = make_problem(seed=0)
    solve_world(s, 2)
    info1 = _cached_sharded_solve.cache_info()
    solve_world(s, 2)
    info2 = _cached_sharded_solve.cache_info()
    assert info2.hits == info1.hits + 1
    assert info2.misses == info1.misses


def test_uneven_edges_padded():
    s = make_synthetic_bal(num_cameras=6, num_points=41, obs_per_point=4,
                           seed=3, param_noise=4e-2, pixel_noise=0.3)
    # An odd observation count forces shard_edge_arrays to pad+mask.
    assert len(s.obs) % 8 != 0
    res = solve_world(s, 8)
    assert np.isfinite(float(res.cost))
    assert float(res.cost) < float(res.initial_cost)


def test_world_size_exceeding_devices_raises():
    with pytest.raises(ValueError):
        make_mesh(1000, cpu_devices(8))
