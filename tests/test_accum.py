"""Compensated f32 reductions vs float64 ground truth.

VERDICT round-1 item 6: plain f32 sums over >=5M terms are too noisy for
LM accept/reject decisions; these tests pin comp_sum's accuracy at BAL
scale against a float64 accumulator (the reference's effective precision,
lm_algo.cu:25-51) and show the plain f32 sum is measurably worse on the
same data.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.ops.accum import comp_dot, comp_sum, comp_sum_sq


def _rel_err(approx, exact):
    return abs(float(approx) - exact) / max(abs(exact), 1e-300)


def test_comp_sum_small_exact():
    x = jnp.asarray([1e8, 1.0, -1e8, 1.0], jnp.float32)
    # Plain left-to-right f32 loses the 1.0s entirely in the worst
    # ordering; the compensated tree recovers the exact 2.0.
    assert float(comp_sum(x)) == 2.0


def test_comp_sum_empty_and_single():
    assert float(comp_sum(jnp.zeros((0,), jnp.float32))) == 0.0
    assert float(comp_sum(jnp.asarray([3.5], jnp.float32))) == 3.5
    assert float(comp_sum(jnp.full((7,), 0.1, jnp.float32))) == pytest.approx(
        0.7, abs=1e-7)


def test_comp_sum_5m_matches_f64():
    # 5M lognormal magnitudes with mixed signs — BA-cost-like spread.
    r = np.random.default_rng(0)
    x64 = r.lognormal(0.0, 2.0, size=5_000_017) * r.choice(
        [-1.0, 1.0], size=5_000_017)
    x32 = x64.astype(np.float32)
    exact = math.fsum(x32.astype(np.float64))  # f64 sum of the f32 data
    comp = jax.jit(comp_sum)(jnp.asarray(x32))
    assert _rel_err(comp, exact) < 2e-7
    # The compensated sum must be at least as accurate as the plain f32
    # reduction on the same data (XLA's sum may already be hierarchical,
    # so the plain error varies — comp must never be worse).
    plain = jnp.sum(jnp.asarray(x32))
    assert _rel_err(comp, exact) <= _rel_err(plain, exact) + 1e-9


def test_comp_sum_adversarial_cancellation():
    # Huge terms that cancel + a tiny survivor: the classic case where
    # f32 loses everything.  n = 2^22 + odd tail to exercise padding.
    n = (1 << 22) + 3
    r = np.random.default_rng(1)
    big = r.normal(scale=1e6, size=n // 2).astype(np.float32)
    x = np.concatenate([big, -big, np.full(n - 2 * (n // 2), 0.03125,
                                           np.float32)])
    r.shuffle(x)
    exact = math.fsum(x.astype(np.float64))
    comp = float(jax.jit(comp_sum)(jnp.asarray(x)))
    assert abs(comp - exact) < 1e-3  # plain f32 is off by O(1e2) here


def test_comp_sum_sq_cost_accuracy():
    # Residual-norm shape: 5M x 2 like Venice, values ~N(0, 1) pixels.
    r = np.random.default_rng(2)
    res = r.normal(scale=1.3, size=(5_000_000, 2)).astype(np.float32)
    exact = float(np.sum(res.astype(np.float64) ** 2))
    comp = float(jax.jit(comp_sum_sq)(jnp.asarray(res)))
    assert _rel_err(comp, exact) < 2e-7


def test_comp_dot_matches_f64():
    r = np.random.default_rng(3)
    a = r.normal(size=1_000_003).astype(np.float32)
    b = r.normal(size=1_000_003).astype(np.float32)
    exact = float(np.dot(a.astype(np.float64), b.astype(np.float64)))
    comp = float(jax.jit(comp_dot)(jnp.asarray(a), jnp.asarray(b)))
    assert _rel_err(comp, exact) < 1e-6


def test_accept_decision_matches_f64_near_convergence():
    """The decision comp_sum exists for: cost_new < cost_old when the
    true relative decrease (~1e-7) is below plain-f32 sum noise."""
    r = np.random.default_rng(4)
    n = 4_000_000
    res_old64 = r.normal(scale=1.0, size=n)
    # A genuine but tiny improvement, spread across all residuals: a few
    # f32 ulps per element so it survives the cast to f32 data, while the
    # total relative decrease (~4e-6) sits below naive-f32-sum noise.
    res_new64 = res_old64 * (1.0 - 2e-6)
    old32, new32 = res_old64.astype(np.float32), res_new64.astype(np.float32)
    exact_old = float(np.sum(old32.astype(np.float64) ** 2))
    exact_new = float(np.sum(new32.astype(np.float64) ** 2))
    assert exact_new < exact_old  # ground truth: accept
    f = jax.jit(comp_sum_sq)
    comp_old, comp_new = float(f(jnp.asarray(old32))), float(f(jnp.asarray(new32)))
    assert comp_new < comp_old  # compensated f32 reaches the same decision
    # and the measured decrease is within 10% of the true decrease.
    true_dec = exact_old - exact_new
    assert abs((comp_old - comp_new) - true_dec) < 0.1 * true_dec
