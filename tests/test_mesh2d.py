"""2-D camera x edge mesh distribution (ISSUE 14).

Tier-1 (compile-free) coverage:

- mesh factorisation: `factor_mesh_2d` auto/explicit splits, the
  elastic `nearest_cam_blocks` refactorisation, `make_mesh_2d` axis
  order, and `validate_options` refusing edge_shards x cam_blocks !=
  world_size;
- camera-tile plan construction: every real edge appears exactly once,
  device blocks hold exactly their column's edges in co-observation
  order, per-device streams stay camera-sorted through the padding,
  and the point-shard bucket tables are mutually consistent with the
  padded stream;
- PI-BA co-observation ordering as a standalone win: reuse-factor
  strictly improves on a locality-mode scene (the EdgeOrder.COOBS
  satellite);
- partition-spec dispatch: the fault/cluster/tile plan spec builders
  follow an overriding 2-D edge spec;
- byte-census decode: replica-group parsing (explicit, iota,
  iota-transposed, permute pairs) and the ring-model
  `collective_bytes_moved` axis, plus the budget gate's exact-match
  enforcement and the committed 1-D-vs-2-D scaling-law comparison;
- elastic re-shard: `resume_elastic` re-factors a 2-D solve onto a
  smaller 2-D mesh (stubbed solve, tests/test_elastic.py style).

The compiling lane (slow-marked; tier-1 is near its time budget) pins
numerical parity: world-4 2x2 vs world-1 at rtol 1e-6 in f64, with
guards, forcing+warm-start and the MULTILEVEL preconditioner each
exercised on the 2-D mesh.
"""

import dataclasses

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from tests.conftest import cpu_devices

from megba_tpu.analysis import budget as budget_mod
from megba_tpu.analysis import hlo
from megba_tpu.common import (
    AlgoOption,
    EdgeOrder,
    JacobianMode,
    PrecondKind,
    ProblemOption,
    RobustOption,
    SolverOption,
    validate_options,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.ops.segtiles import (
    build_camera_tile_plan,
    cached_camera_tile_plan,
    cluster_partition_specs,
    coobservation_edge_order,
    device_camera_tile_plan,
    edge_stream_reuse,
    tile_plan_partition_specs,
)
from megba_tpu.parallel.mesh import (
    CAM_AXIS,
    EDGE_AXIS,
    factor_mesh_2d,
    make_mesh_2d,
    mesh_axes,
    nearest_cam_blocks,
)
from megba_tpu.solve import flat_solve


# ---------------------------------------------------------------------------
# Mesh factorisation (compile-free)
# ---------------------------------------------------------------------------

def test_factor_mesh_2d_auto_is_squareish():
    # 0 = auto: largest divisor <= sqrt(world) becomes cam_blocks.
    assert factor_mesh_2d(1, 0) == (1, 1)
    assert factor_mesh_2d(4, 0) == (2, 2)
    assert factor_mesh_2d(6, 0) == (3, 2)
    assert factor_mesh_2d(8, 0) == (4, 2)
    assert factor_mesh_2d(9, 0) == (3, 3)
    assert factor_mesh_2d(7, 0) == (7, 1)  # prime: degenerate 1-D column


def test_factor_mesh_2d_explicit_and_errors():
    assert factor_mesh_2d(8, 4) == (2, 4)
    assert factor_mesh_2d(8, 1) == (8, 1)
    with pytest.raises(ValueError, match="does not factor"):
        factor_mesh_2d(8, 3)
    with pytest.raises(ValueError, match="does not factor"):
        factor_mesh_2d(4, 8)
    with pytest.raises(ValueError, match="world_size"):
        factor_mesh_2d(0, 0)


def test_nearest_cam_blocks_shrink_refactorisation():
    # The elastic contract: keep as much of the camera split as the
    # surviving world still factors, degrade to 1 only when no divisor
    # survives.
    assert nearest_cam_blocks(2, 2) == 2   # 2x2 -> 1x2
    assert nearest_cam_blocks(6, 4) == 3   # cap at the largest divisor
    assert nearest_cam_blocks(3, 2) == 1   # prime world: 1-D layout
    assert nearest_cam_blocks(4, 0) == 1   # degenerate request floors at 1
    assert nearest_cam_blocks(12, 4) == 4


def test_make_mesh_2d_axis_order_and_validation():
    mesh = make_mesh_2d(2, 2, cpu_devices(4))
    assert mesh.axis_names == (EDGE_AXIS, CAM_AXIS)
    assert mesh.devices.shape == (2, 2)
    assert mesh_axes(mesh) == (EDGE_AXIS, CAM_AXIS)
    with pytest.raises(ValueError, match="needs 4 devices"):
        make_mesh_2d(2, 2, cpu_devices(2))
    with pytest.raises(ValueError, match=">= 1"):
        make_mesh_2d(0, 2, cpu_devices(2))


def test_mesh_axes_1d_is_the_historical_scalar():
    from megba_tpu.parallel.mesh import make_mesh

    assert mesh_axes(make_mesh(2, cpu_devices(2))) == EDGE_AXIS


def test_validate_options_rejects_bad_factorisation():
    def opt(**skw):
        return ProblemOption(world_size=4,
                             solver_option=SolverOption(**skw))

    validate_options(opt(mesh_2d=True, cam_blocks=2))
    validate_options(opt(mesh_2d=True, cam_blocks=0))  # auto is fine
    with pytest.raises(ValueError, match="does not divide"):
        validate_options(opt(mesh_2d=True, cam_blocks=3))
    with pytest.raises(ValueError, match="does not divide"):
        validate_options(opt(mesh_2d=True, cam_blocks=8))
    with pytest.raises(ValueError, match="cam_blocks must be >= 0"):
        validate_options(opt(cam_blocks=-1))
    with pytest.raises(ValueError, match="Schur"):
        validate_options(dataclasses.replace(
            opt(mesh_2d=True, cam_blocks=2), use_schur=False))


def test_flat_solve_refuses_mesh2d_with_pallas_tiles():
    s = make_synthetic_bal(num_cameras=4, num_points=20, obs_per_point=3,
                           seed=0)
    option = ProblemOption(
        world_size=4,
        solver_option=SolverOption(mesh_2d=True, cam_blocks=2))
    with pytest.raises(ValueError, match="does not compose"):
        flat_solve(make_residual_jacobian_fn(), s.cameras0, s.points0,
                   s.obs, s.cam_idx, s.pt_idx, option, use_tiled=True)


# ---------------------------------------------------------------------------
# Camera-tile plan construction (compile-free)
# ---------------------------------------------------------------------------

def _scene(locality=None, seed=0, nc=12, npts=64, opp=4):
    return make_synthetic_bal(num_cameras=nc, num_points=npts,
                              obs_per_point=opp, seed=seed,
                              locality=locality)


def _plan(s, E=2, C=2, quantum=4):
    return build_camera_tile_plan(s.cam_idx, s.pt_idx, len(s.cameras0),
                                  len(s.points0), E, C, quantum=quantum)


def test_tile_plan_every_real_edge_exactly_once():
    s = _scene()
    plan = _plan(s)
    real = plan.perm[plan.mask > 0]
    assert plan.n_edges_real == len(s.cam_idx)
    assert sorted(real.tolist()) == list(range(len(s.cam_idx)))
    # The padded streams agree with the permutation on real slots.
    np.testing.assert_array_equal(plan.cam_idx[plan.mask > 0],
                                  s.cam_idx[real])
    np.testing.assert_array_equal(plan.pt_idx[plan.mask > 0],
                                  s.pt_idx[real])


def test_tile_plan_device_blocks_own_their_camera_column():
    s = _scene(nc=13)  # Nc not divisible by C: last tile is ragged
    E, C = 2, 2
    plan = _plan(s, E=E, C=C)
    chunk = plan.n_edges_padded // (E * C)
    for b in range(E * C):
        c = b % C  # edge-shard-major, camera-minor block order
        sl = slice(b * chunk, (b + 1) * chunk)
        cams = plan.cam_idx[sl]
        m = plan.mask[sl]
        # Real edges of this block live inside camera tile c...
        col = np.minimum(s.cam_idx[plan.perm[sl][m > 0]] // plan.tile_cams,
                         C - 1)
        assert (col == c).all()
        # ...the whole padded stream stays inside the tile and
        # camera-sorted (the indices_are_sorted scatter promise).
        assert (plan.cam_local[sl] >= 0).all()
        assert (plan.cam_local[sl] < plan.tile_cams).all()
        assert (np.diff(cams) >= 0).all()
        # Co-observation order within the block: point-minor inside
        # each camera run (real slots only).
        cr, pr = cams[m > 0], plan.pt_idx[sl][m > 0]
        same_cam = cr[1:] == cr[:-1]
        assert (np.diff(pr)[same_cam] >= 0).all()


def test_tile_plan_buckets_consistent_with_stream():
    s = _scene(seed=3)
    E, C = 2, 2
    plan = _plan(s, E=E, C=C)
    chunk = plan.n_edges_padded // (E * C)
    Sp = plan.shard_points
    for d in range(E * C):
        sl = slice(d * chunk, (d + 1) * chunk)
        pts, m = plan.pt_idx[sl], plan.mask[sl]
        covered = np.zeros(chunk, bool)
        for sh in range(C):
            row = d * C + sh
            bm = plan.bucket_mask[row] > 0
            slots = plan.bucket_slot[row][bm]
            # Each bucket's slots are real local edges of shard sh...
            assert (m[slots] > 0).all()
            assert (pts[slots] // Sp == sh).all()
            # ...with shard-local point indices.
            np.testing.assert_array_equal(
                plan.bucket_ptl[row][bm], pts[slots] - sh * Sp)
            assert not covered[slots].any()
            covered[slots] = True
        # Together the C buckets cover every real edge exactly once.
        np.testing.assert_array_equal(covered, m > 0)


def test_tile_plan_padding_is_quantum_aligned():
    s = _scene()
    plan = _plan(s, E=2, C=2, quantum=4)
    chunk = plan.n_edges_padded // 4
    assert chunk % 4 == 0
    assert plan.n_edges_padded % (2 * 2 * 4) == 0


def test_tile_plan_rejects_degenerate_grid():
    s = _scene()
    with pytest.raises(ValueError, match=">= 1"):
        build_camera_tile_plan(s.cam_idx, s.pt_idx, 12, 64, 0, 2)


def test_cached_camera_tile_plan_fingerprint():
    s = _scene(seed=7)
    (p1, d1), hit1 = cached_camera_tile_plan(
        s.cam_idx, s.pt_idx, 12, 64, 2, 2, quantum=4)
    (p2, d2), hit2 = cached_camera_tile_plan(
        s.cam_idx, s.pt_idx, 12, 64, 2, 2, quantum=4)
    assert not hit1 and hit2
    assert p2 is p1 and d2 is d1
    # A different geometry knob is a different plan.
    (_, _), hit3 = cached_camera_tile_plan(
        s.cam_idx, s.pt_idx, 12, 64, 1, 4, quantum=4)
    assert not hit3


def test_device_plan_is_a_pytree_operand():
    import jax

    s = _scene()
    dplan = device_camera_tile_plan(_plan(s))
    leaves, treedef = jax.tree_util.tree_flatten(dplan)
    assert len(leaves) == 4  # cam_local + the three bucket tables
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.cam_blocks == dplan.cam_blocks
    assert rebuilt.tile_cams == dplan.tile_cams


# ---------------------------------------------------------------------------
# Co-observation ordering as a standalone win (compile-free)
# ---------------------------------------------------------------------------

def test_coobs_order_is_camera_major_point_minor():
    cam = np.array([3, 0, 0, 2, 0, 2])
    pt = np.array([5, 9, 1, 7, 4, 2])
    perm = coobservation_edge_order(cam, pt)
    np.testing.assert_array_equal(cam[perm], [0, 0, 0, 2, 2, 3])
    np.testing.assert_array_equal(pt[perm], [1, 4, 9, 2, 7, 5])


def test_coobs_reuse_strictly_improves_on_locality_scene():
    # The EdgeOrder.COOBS satellite: on a ring-locality scene the PI-BA
    # ordering consumes strictly more edges per fetched (camera, point)
    # tile pair than an arbitrary caller order.  (The synthetic
    # generator happens to emit a camera-sorted stream, so the honest
    # baseline is a seeded shuffle of it — real g2o/BAL files arrive in
    # whatever order the frontend wrote them.)
    s = _scene(locality="ring", seed=1, nc=16, npts=120)
    shuf = np.random.default_rng(0).permutation(len(s.cam_idx))
    cam, pt = s.cam_idx[shuf], s.pt_idx[shuf]
    base = edge_stream_reuse(cam, pt, cam_tile=4, pt_tile=16)
    perm = coobservation_edge_order(cam, pt)
    ordered = edge_stream_reuse(cam[perm], pt[perm],
                                cam_tile=4, pt_tile=16)
    assert base["edges"] == ordered["edges"]
    assert ordered["reuse_factor"] > base["reuse_factor"]
    assert ordered["switches"] < base["switches"]


def test_edge_stream_reuse_counts():
    cam = np.array([0, 0, 0, 4, 4])
    pt = np.array([0, 1, 9, 0, 1])
    # cam_tile=2, pt_tile=8: pairs (0,0) (0,0) (0,1) (2,0) (2,0).
    r = edge_stream_reuse(cam, pt, cam_tile=2, pt_tile=8)
    assert r == {"edges": 5, "switches": 3, "reuse_factor": 5 / 3}
    # Masked edges drop out of the stream.
    r = edge_stream_reuse(cam, pt, 2, 8, mask=np.array([1, 1, 0, 1, 1]))
    assert r["edges"] == 4 and r["switches"] == 2
    assert edge_stream_reuse(cam[:0], pt[:0], 2, 8)["edges"] == 0


def test_edge_order_knob_defaults_natural():
    assert SolverOption().edge_order == EdgeOrder.NATURAL


# ---------------------------------------------------------------------------
# Partition-spec dispatch (compile-free)
# ---------------------------------------------------------------------------

def test_partition_specs_follow_2d_edge_split():
    from megba_tpu.robustness.faults import fault_partition_specs

    e2d = P((EDGE_AXIS, CAM_AXIS))
    fp = fault_partition_specs(edge_spec=e2d)
    assert fp.edge_nan == e2d and fp.point_crush == P()
    # Default stays the historical 1-D spec.
    assert fault_partition_specs().edge_nan == P(EDGE_AXIS)

    s = _scene()
    dplan = device_camera_tile_plan(_plan(s))
    tp = tile_plan_partition_specs(dplan, e2d)
    assert tp.cam_local == e2d
    assert tp.bucket_slot == e2d and tp.bucket_mask == e2d
    assert tp.cam_blocks == dplan.cam_blocks  # meta rides through


def test_cluster_specs_edge_override():
    from megba_tpu.ops.segtiles import (
        build_cluster_plan,
        device_cluster_plan,
    )

    s = _scene()
    cplan = device_cluster_plan(
        build_cluster_plan(s.cam_idx, s.pt_idx, 12, 64))
    e2d = P((EDGE_AXIS, CAM_AXIS))
    specs = cluster_partition_specs(cplan, edge_spec=e2d)
    assert specs.pc_slot == e2d and specs.ec_edge == e2d
    assert specs.cluster == P()  # replicated tables stay replicated
    assert cluster_partition_specs(cplan).pc_slot == P(EDGE_AXIS)


# ---------------------------------------------------------------------------
# Byte-census decode (compile-free)
# ---------------------------------------------------------------------------

def _op(kind, elems, dtype="f32", groups=None):
    return hlo.HloOp(kind=kind, line=1, text="", result_dtype=dtype,
                     result_elems=elems, replica_groups=groups)


def test_parse_groups_explicit_list():
    raw = "replica_groups={{0,1},{2,3}}, to_apply=%r"
    assert hlo._parse_groups(raw) == ((0, 1), (2, 3))


def test_parse_groups_iota_form():
    # [2,2]<=[4]: iota 0..3 reshaped to two groups of two.
    assert hlo._parse_groups("replica_groups=[2,2]<=[4]") == ((0, 1), (2, 3))
    # Transposed iota: [2,2]<=[2,2]T(1,0) pairs strided device ids —
    # exactly the form XLA emits for the CAM subgroup of a 2x2 mesh.
    assert hlo._parse_groups(
        "replica_groups=[2,2]<=[2,2]T(1,0)") == ((0, 2), (1, 3))


def test_parse_groups_permute_pairs_and_group_size():
    op = hlo.HloOp(kind="collective_permute", line=1, text="",
                   replica_groups=hlo._parse_groups(
                       "source_target_pairs={{0,1},{1,0},{2,3},{3,2}}"))
    # Two disjoint 2-cycles: the permute moves data among 2 devices.
    assert op.group_size() == 2
    # An OPEN chain 0->1->2->3 (no wraparound) still spans 4 devices —
    # a world-spanning permute must never be certified subgroup-scoped,
    # regardless of the order the pairs are listed in.
    chain = hlo.HloOp(kind="collective_permute", line=1, text="",
                      replica_groups=((1, 2), (0, 1), (2, 3)))
    assert chain.group_size() == 4
    assert _op("all_reduce", 4, groups=((0, 1), (2, 3))).group_size() == 2
    assert _op("all_reduce", 4).group_size() is None
    # XLA's explicit empty form is ONE world-spanning group — it must
    # resolve to the world size, not read as "no parseable groups".
    assert hlo._parse_groups("replica_groups={}, to_apply=%r") == ((),)
    world_op = _op("all_reduce", 4, groups=((),))
    assert world_op.group_size(world=4) == 4
    assert world_op.group_size() is None


def test_collective_bytes_moved_ring_model():
    # all_reduce: 2 B (g-1)/g — 256 f32 elems = 1024 B at g=2 -> 1024.
    ar = _op("all_reduce", 256, groups=((0, 1),))
    assert hlo.collective_bytes_moved(ar, world=4) == 1024.0
    # No groups: defaults to world scope (g=4 -> 2*1024*3/4).
    assert hlo.collective_bytes_moved(
        _op("all_reduce", 256), world=4) == 1536.0
    # reduce_scatter prices against the OUTPUT shard: B_out (g-1).
    rs = _op("reduce_scatter", 128, groups=((0, 1),))
    assert hlo.collective_bytes_moved(rs, world=4) == 512.0
    # all_gather: B_out (g-1)/g.
    ag = _op("all_gather", 256, groups=((0, 1),))
    assert hlo.collective_bytes_moved(ag, world=4) == 512.0
    # collective_permute: every device forwards its block once.
    cp = hlo.HloOp(kind="collective_permute", line=1, text="",
                   result_dtype="f64", result_elems=64,
                   replica_groups=((0, 1), (1, 0)))
    assert hlo.collective_bytes_moved(cp, world=4) == 512.0
    # Unknown kind / missing shape: priced 0, never a crash.
    assert hlo.collective_bytes_moved(_op("all_to_all", None), 4) == 0.0
    assert hlo.collective_bytes_moved(
        _op("mystery_collective", 64), 4) == 0.0


def test_tuple_result_collective_pricing():
    # AllReduceCombiner tuple: components are independent outputs, so
    # the payload is their SUM (f32[256]+s32[128] = 1024+512 bytes);
    # result_elems keeps the first component only.
    text = ('  %ar = (f32[256]{0}, s32[128]{0}) all-reduce(%a, %b), '
            'replica_groups={{0,1}}, to_apply=%add\n')
    (op,) = hlo.parse_compiled_ops(text)
    assert op.kind == "all_reduce" and op.result_elems == 256
    assert op.result_bytes == 1536.0
    # 2 B (g-1)/g at g=2 -> B.
    assert hlo.collective_bytes_moved(op, world=4) == 1536.0
    # Async -start tuple aliases the INPUT SHARD beside the gathered
    # output (plus context scalars): the payload is the LARGEST
    # component, not the first — first-component pricing would
    # undercount an all-gather-start by the group factor.
    text = ('  %ag = (f32[64]{0}, f32[256]{0}, u32[]) '
            'all-gather-start(%shard), replica_groups={{0,1,2,3}}, '
            'dimensions={0}\n')
    (op,) = hlo.parse_compiled_ops(text)
    assert op.kind == "all_gather"
    assert op.result_bytes == 1024.0
    # B_out (g-1)/g at g=4 -> 768.
    assert hlo.collective_bytes_moved(op, world=4) == 768.0


def test_budget_gate_exact_match_on_collective_bytes():
    # The bytes-moved axis is exact-gated: one extra byte per CG step
    # inside the body is a named violation.
    baseline = budget_mod.load_baseline()
    measured = {n: dict(m) for n, m in baseline.items()}
    measured["ba_sharded_w2_f32"]["collective_bytes_per_sp"] += 1.0
    violations = budget_mod.compare(baseline, measured)
    assert any("ba_sharded_w2_f32" in v and "collective_bytes_per_sp" in v
               for v in violations)


def test_committed_2d_budget_beats_the_1d_scaling_law():
    """The tentpole's structural pin, from the COMMITTED budgets: the
    2x2 program moves strictly fewer bytes per CG step than the 1-D
    all-reduce law predicts at world 4.

    The 1-D body is two all-reduces whose summed operand bytes B cost
    2 B (g-1)/g per device: the committed world-2 entry measures
    exactly B (2 B * 1/2), so the world-4 law is B * 2 * 3/4.
    """
    baseline = budget_mod.load_baseline()
    b1d = baseline["ba_sharded_w2_f32"]["collective_bytes_per_sp"]
    b2d = baseline["ba_2d_w4_f32"]["collective_bytes_per_sp"]
    assert b1d > 0 and b2d > 0
    law_w4 = b1d * 2.0 * (4 - 1) / 4
    assert b2d < law_w4, (b2d, law_w4)


# ---------------------------------------------------------------------------
# Elastic re-shard (stub world, tests/test_elastic.py style)
# ---------------------------------------------------------------------------

def _resume_with_stub(monkeypatch, option, new_world):
    from megba_tpu.algo import checkpointed as ckpt_mod
    from megba_tpu.robustness.elastic import resume_elastic

    seen = {}

    def stub_solve(fn, cams, pts, obs, ci, pi, opt, **kw):
        seen["option"] = opt
        return "stub-result"

    monkeypatch.setattr(ckpt_mod, "solve_checkpointed", stub_solve)
    s = _scene(nc=4, npts=16, opp=3)
    out = resume_elastic(
        make_residual_jacobian_fn(), s.cameras0, s.points0, s.obs,
        s.cam_idx, s.pt_idx, option, "/tmp/unused-snap.npz",
        world_size=new_world)
    assert out == "stub-result"
    return seen["option"]


def _opt_2d(world, cam_blocks):
    return ProblemOption(
        world_size=world,
        solver_option=SolverOption(mesh_2d=True, cam_blocks=cam_blocks))


def test_resume_elastic_refactors_2d_mesh(monkeypatch):
    # 2x2 world shrinking to 2: the camera split survives whole — the
    # resumed mesh is 1x2, not the 1-D fallback.
    opt = _resume_with_stub(monkeypatch, _opt_2d(4, 2), new_world=2)
    assert opt.world_size == 2
    assert opt.solver_option.mesh_2d
    assert opt.solver_option.cam_blocks == 2


def test_resume_elastic_degrades_to_1d_on_prime_world(monkeypatch):
    # 2x2 shrinking to 3 devices: no divisor survives — cam_blocks
    # degrades to 1 (1-D communication on the 2-D program).
    opt = _resume_with_stub(monkeypatch, _opt_2d(4, 2), new_world=3)
    assert opt.world_size == 3
    assert opt.solver_option.cam_blocks == 1


def test_resume_elastic_resolves_auto_factorisation(monkeypatch):
    # cam_blocks=0 (auto) at world 4 is a 2x2 mesh; the shrink-world
    # resume re-factors from the RESOLVED split, not the 0 sentinel.
    opt = _resume_with_stub(monkeypatch, _opt_2d(4, 0), new_world=2)
    assert opt.solver_option.cam_blocks == 2


def test_resume_elastic_1d_option_untouched(monkeypatch):
    base = ProblemOption(world_size=4, solver_option=SolverOption())
    opt = _resume_with_stub(monkeypatch, base, new_world=2)
    assert opt.world_size == 2
    assert not opt.solver_option.mesh_2d
    assert opt.solver_option.cam_blocks == 0


# ---------------------------------------------------------------------------
# Numerical parity on the 2-D mesh (compiling — slow lane)
# ---------------------------------------------------------------------------

def _solve(s, world, mesh2d=False, cam_blocks=0,
           precond=PrecondKind.JACOBI, guards=False, forcing=False,
           edge_order=EdgeOrder.NATURAL, max_iter=6,
           dtype=np.float64, mixed_precision=False, **skw):
    option = ProblemOption(
        world_size=world, jacobian_mode=JacobianMode.ANALYTICAL,
        dtype=dtype, mixed_precision_pcg=mixed_precision,
        robust_option=RobustOption(guards=guards),
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-9,
                               epsilon2=1e-12),
        solver_option=SolverOption(max_iter=200, tol=1e-10,
                                   tol_relative=True, refuse_ratio=1e30,
                                   precond=precond, forcing=forcing,
                                   mesh_2d=mesh2d, cam_blocks=cam_blocks,
                                   edge_order=edge_order, **skw))
    return flat_solve(make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL),
                      s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                      option, use_tiled=False)


@pytest.mark.slow  # two fresh SPMD LM compiles — cache-cold this is
# minutes; the full suite (scripts/run_tests.sh) runs it, tier-1 skips
def test_2d_parity_world4_matches_single_device():
    s = make_synthetic_bal(num_cameras=10, num_points=60, obs_per_point=5,
                           seed=3, param_noise=5e-2, pixel_noise=0.3)
    one = _solve(s, 1, max_iter=8)
    two = _solve(s, 4, mesh2d=True, cam_blocks=2, max_iter=8)
    np.testing.assert_allclose(float(two.cost), float(one.cost), rtol=1e-6)
    assert int(two.iterations) == int(one.iterations)
    assert int(two.pcg_iterations) == int(one.pcg_iterations)


@pytest.mark.slow  # eight fresh SPMD LM compiles (four pairs)
@pytest.mark.parametrize("mode", ["guards", "forcing", "multilevel",
                                  "mixed"])
def test_2d_guards_forcing_multilevel_compose(mode):
    # The acceptance matrix: each composition exercised on the 2-D mesh
    # at least once, against its world-1 control at rtol 1e-6.  The
    # mixed-precision pair is looser (rtol 1e-2, the tiled-parity
    # precedent): both paths keep f32 Krylov vectors and f32
    # accumulation over bf16 edge rows (the 2-D `contrib.astype(
    # p.dtype)` casts f32->f32 — the CG state is f32 by construction),
    # but with tol_relative=1e-10 both stagnate at the ~1e-3 accuracy
    # OF THE bf16-ROUNDED OPERATOR itself, where a different
    # per-column summation grouping legitimately lands elsewhere
    # (measured: plain-f32 2-D parity 7e-6; mixed 2-D 4e-3 with the
    # 2-D side at the LOWER cost — not an accumulation downcast, which
    # would only lose ground).  Equal LM iteration counts still pin
    # the trajectory shape.
    dtype = np.float32 if mode == "mixed" else np.float64
    s = make_synthetic_bal(num_cameras=16, num_points=120, obs_per_point=4,
                           seed=3, param_noise=5e-2, pixel_noise=0.3,
                           locality="ring", dtype=dtype)
    kw = {
        "guards": dict(guards=True),
        "forcing": dict(forcing=True),
        "multilevel": dict(precond=PrecondKind.MULTILEVEL,
                           coarsen_factor=2.0, max_levels=4),
        "mixed": dict(dtype=dtype, mixed_precision=True),
    }[mode]
    one = _solve(s, 1, **kw)
    two = _solve(s, 4, mesh2d=True, cam_blocks=2, **kw)
    rtol = 1e-2 if mode == "mixed" else 1e-6
    np.testing.assert_allclose(float(two.cost), float(one.cost), rtol=rtol)
    assert int(two.iterations) == int(one.iterations)


@pytest.mark.slow  # one tiny single-device LM compile
def test_tile_plan_ignored_off_the_2d_mesh():
    # The documented direct-API contract (algo/lm.lm_solve docstring):
    # a tile_plan rides only when axis_name is the (EDGE, CAM) tuple —
    # on a 1-D mesh or single device it is IGNORED, not an axis-unpack
    # crash inside make_matvec_2d.
    import jax.numpy as jnp

    from megba_tpu.algo.lm import lm_solve
    from megba_tpu.ops.segtiles import device_camera_tile_plan

    s = make_synthetic_bal(num_cameras=5, num_points=30, obs_per_point=3,
                           seed=0, param_noise=3e-2, pixel_noise=0.2,
                           dtype=np.float32)
    plan = build_camera_tile_plan(s.cam_idx, s.pt_idx, 5, 30, 1, 2)
    option = ProblemOption(
        dtype=np.float32, algo_option=AlgoOption(max_iter=2),
        solver_option=SolverOption(max_iter=5, tol=1e-8))
    res = lm_solve(
        make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF),
        jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
        jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx),
        jnp.asarray(s.pt_idx), jnp.ones(s.obs.shape[0], np.float32),
        option, tile_plan=device_camera_tile_plan(plan))
    assert np.isfinite(float(res.cost))


@pytest.mark.slow  # one extra single-device LM compile (COOBS reorders
# the edge stream, which is a fresh operand shape class only once)
def test_coobs_1d_solve_matches_natural():
    s = make_synthetic_bal(num_cameras=10, num_points=60, obs_per_point=5,
                           seed=0, param_noise=5e-2, pixel_noise=0.3)
    nat = _solve(s, 1, max_iter=8)
    coobs = _solve(s, 1, edge_order=EdgeOrder.COOBS, max_iter=8)
    # A host permutation only reorders sums: solver-tolerance parity.
    np.testing.assert_allclose(float(coobs.cost), float(nat.cost),
                               rtol=1e-6)
