"""Elastic kill-resume worker: one rank of a world-N checkpointed solve.

Run as `python tests/_elastic_worker.py <rank> <port> <world> <ckpt.npz>
<result.npz|-> <config> <heartbeat_dir>`.  All ranks join one elastic
jax.distributed cluster (client-only; the coordination service lives in
the sacrificial rendezvous process the harness runs — see
parallel/multihost.serve_rendezvous) and run the SAME deterministic
checkpointed BA solve at world_size=<world> over gloo CPU collectives,
one device per rank, under an ElasticMonitor.

When a peer is SIGKILLed mid-solve (tests/test_elastic_killresume.py,
scripts/run_tests.sh elastic smoke), the survivor must (1) surface a
typed WorkerLost/CollectiveTimeout within the watchdog budget — printed
as the ELASTIC-DETECT line the harness asserts on — then (2)
resume_elastic at world 1 from the latest schema-v3 snapshot and run to
completion, writing the final result for the parity check against an
uninterrupted run.  Everything that could differ between runs is pinned
(x64, CPU backend, one device per rank, persistent compile cache).
"""

import os
import sys

# Runnable from any cwd: the repo root is this file's parent's parent.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Worker-process pinning ONLY when run as a script: the pytest/smoke
# orchestrators IMPORT this module for `build_problem` (so reference and
# worker solve byte-identical problems) and own their own backend setup.
if __name__ == "__main__":
    # One CPU device per rank, pinned BEFORE jax import.
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from megba_tpu.utils.backend import enable_persistent_compile_cache

    enable_persistent_compile_cache()

import numpy as np  # noqa: E402

from megba_tpu.algo.checkpointed import solve_checkpointed  # noqa: E402
from megba_tpu.common import (  # noqa: E402
    AlgoOption,
    ComputeKind,
    JacobianMode,
    ProblemOption,
    SolverOption,
)
from megba_tpu.io.synthetic import make_synthetic_bal  # noqa: E402
from megba_tpu.ops.residuals import make_residual_jacobian_fn  # noqa: E402
from megba_tpu.parallel.multihost import (  # noqa: E402
    enable_cpu_cross_process_collectives,
    initialize_multihost,
)
from megba_tpu.robustness.elastic import (  # noqa: E402
    CollectiveTimeout,
    ElasticConfig,
    ElasticMonitor,
    WorkerLost,
    resume_elastic,
)

CHECKPOINT_EVERY = 2


def build_problem(config: str, world: int):
    """(synthetic problem, ProblemOption) for a named config — shared by
    the workers, the pytest parity reference and the run_tests.sh smoke
    so all three solve byte-identical problems."""
    if config == "tiny":
        s = make_synthetic_bal(
            num_cameras=6, num_points=90, obs_per_point=5, seed=7,
            param_noise=3e-2, pixel_noise=0.3, dtype=np.float64)
        option = ProblemOption(
            dtype=np.float64,
            world_size=world,
            compute_kind=ComputeKind.IMPLICIT,
            jacobian_mode=JacobianMode.ANALYTICAL,
            algo_option=AlgoOption(max_iter=8, epsilon1=1e-12,
                                   epsilon2=1e-15),
            solver_option=SolverOption(max_iter=30, tol=1e-12,
                                       refuse_ratio=1e30),
        )
    elif config == "venice10":
        # The venice-10% scale the fault smoke uses, in f64 so the
        # shrink-world parity gate can ride the rtol 1e-6 contract.
        s = make_synthetic_bal(
            num_cameras=177, num_points=99392,
            obs_per_point=5_001_946 / 993_923, seed=0,
            param_noise=1e-2, pixel_noise=0.5, dtype=np.float64)
        option = ProblemOption(
            dtype=np.float64,
            world_size=world,
            compute_kind=ComputeKind.IMPLICIT,
            jacobian_mode=JacobianMode.ANALYTICAL,
            algo_option=AlgoOption(max_iter=6, epsilon1=1e-12,
                                   epsilon2=1e-15),
            solver_option=SolverOption(max_iter=30, tol=1e-10,
                                       refuse_ratio=1e30),
        )
    else:
        raise ValueError(f"unknown config {config!r}")
    return s, option


def elastic_config(rank: int, world: int, heartbeat_dir: str) -> ElasticConfig:
    """The budgets the kill harness asserts against: a dead peer must
    surface within ~dead_after_s (well inside watchdog_s); the first
    dispatch of each (re)lowered program gets the compile grace."""
    return ElasticConfig(
        heartbeat_dir=heartbeat_dir, rank=rank, world=world,
        interval_s=0.1, straggler_after_s=0.6, dead_after_s=1.5,
        watchdog_s=30.0, compile_grace_s=1200.0, poll_s=0.05)


def dump_result(path: str, res, detect_kind: str,
                detect_latency_s: float) -> None:
    payload = {
        "cameras": np.asarray(res.cameras),
        "points": np.asarray(res.points),
        "cost": np.asarray(float(res.cost)),
        "iterations": np.asarray(int(res.iterations)),
        "status": np.asarray(int(res.status)),
        "detect_kind": np.asarray(detect_kind),
        "detect_latency_s": np.asarray(float(detect_latency_s)),
    }
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def main() -> None:
    rank = int(sys.argv[1])
    port = sys.argv[2]
    world = int(sys.argv[3])
    ckpt = sys.argv[4]
    out = sys.argv[5]
    config = sys.argv[6]
    hb_dir = sys.argv[7]

    # gloo CPU collectives, selected before backend init; elastic
    # (survivable) bring-up against the external rendezvous daemon.
    assert enable_cpu_cross_process_collectives(), \
        "jaxlib has no gloo CPU collectives"
    info = initialize_multihost(f"localhost:{port}", world, rank,
                                elastic=True)
    assert info["process_count"] == world, info

    s, option = build_problem(config, world)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    args = (f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx)
    cfg = elastic_config(rank, world, hb_dir)
    detect_kind, detect_latency = "none", float("nan")
    with ElasticMonitor(cfg) as monitor:
        try:
            res = solve_checkpointed(
                *args, option, checkpoint_path=ckpt,
                checkpoint_every=CHECKPOINT_EVERY, use_tiled=False,
                elastic=monitor)
            print(f"worker {rank} CLEAN cost {float(res.cost):.17e} "
                  f"iters {int(res.iterations)}", flush=True)
        except (WorkerLost, CollectiveTimeout) as exc:
            detect_kind = ("worker_lost" if isinstance(exc, WorkerLost)
                           else "collective_timeout")
            detect_latency = getattr(exc, "detected_after_s",
                                     getattr(exc, "elapsed_s", float("nan")))
            print(f"worker {rank} ELASTIC-DETECT kind={detect_kind} "
                  f"latency={detect_latency:.3f} "
                  f"budget={cfg.watchdog_s:.3f}", flush=True)
            res = resume_elastic(
                *args, option, ckpt, world_size=1, monitor=monitor,
                checkpoint_every=CHECKPOINT_EVERY, use_tiled=False)
            print(f"worker {rank} ELASTIC-RESUME world=1 "
                  f"cost={float(res.cost):.17e} "
                  f"iters={int(res.iterations)} "
                  f"status={int(res.status)}", flush=True)
    if out != "-":
        dump_result(out, res, detect_kind, detect_latency)
    print(f"worker {rank} OK", flush=True)


if __name__ == "__main__":
    main()
