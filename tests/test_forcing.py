"""Inexact LM: adaptive Eisenstat-Walker forcing + PCG warm starts.

Convergence-parity contract (ISSUE 4): with `SolverOption(forcing=True,
warm_start=True)` the solver must reach the SAME optimum as the
fixed-tight-tolerance configuration — on BAL, PGO and planar problems,
single-device and world-2 — while spending strictly fewer total PCG
iterations; warm starts must be bitwise-disabled on rejected steps; and
the `tol_relative` threshold must be anchored to the RHS energy
<b, M^-1 b>, not the warm start's initial residual.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.algo import lm_solve
from megba_tpu.common import (
    AlgoOption,
    JacobianMode,
    ProblemOption,
    SolverOption,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.models import planar
from megba_tpu.ops.residuals import make_residual_jacobian_fn

# Tight fixed-tolerance reference configuration (the pre-forcing
# regime the parity contract is defined against) and its inexact
# counterpart: same refuse/iteration budget, adaptive tolerance.
TIGHT = dict(max_iter=100, tol=1e-12, tol_relative=True, refuse_ratio=1e30)
INEXACT = dict(max_iter=100, tol=1e-1, refuse_ratio=1e30,
               forcing=True, warm_start=True)
# Parity band: the curve-parity gap_tol regime (utils/curves uses
# 100 * rel_tol; at f64 the observed gap is ~1e-13).
GAP_RTOL = 1e-6


def _bal_problem(seed=0):
    return make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                              seed=seed, param_noise=5e-2, pixel_noise=0.3)


def _solve_bal(s, solver_opt, f, max_iter=25):
    option = ProblemOption(
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-9,
                               epsilon2=1e-12),
        solver_option=SolverOption(**solver_opt))
    return jax.jit(
        lambda cams, pts, obs, ci, pi, m: lm_solve(
            f, cams, pts, obs, ci, pi, m, option)
    )(jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
      jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx),
      jnp.ones(len(s.obs)))


def test_forcing_parity_and_reduction_bal():
    s = _bal_problem()
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    base = _solve_bal(s, TIGHT, f)
    inex = _solve_bal(s, INEXACT, f)
    np.testing.assert_allclose(float(inex.cost), float(base.cost),
                               rtol=GAP_RTOL)
    # The headline contract: strictly fewer total inner iterations
    # (observed here: ~4x fewer), at the same optimum.
    assert int(inex.pcg_iterations) < int(base.pcg_iterations)
    assert int(inex.accepted) > 0
    # Warm-start resume state is exposed (and feature-major like cameras)
    # under warm_start; absent otherwise.
    assert inex.dx_cam is not None and inex.dx_cam.shape == (9, 6)
    assert base.dx_cam is None


def test_forcing_parity_planar():
    # Noiseless scene: the optimum is cost ~ 0, so "same final cost"
    # means both configurations drive the >14-orders-of-magnitude
    # reduction (the noisy-floor parity case is the BAL test above; a
    # noisy PLANAR scene never plateaus within a bounded LM budget, so
    # a cost-at-iteration-k comparison there would only measure crawl
    # speed, not the optimum).
    s = planar.make_synthetic_planar(seed=1, noise=0.0, param_noise=5e-3)
    f = make_residual_jacobian_fn(residual_fn=planar.residual,
                                  mode=JacobianMode.AUTODIFF)
    base = _solve_bal(s, TIGHT, f, max_iter=40)
    inex = _solve_bal(s, INEXACT, f, max_iter=40)
    assert float(base.cost) < 1e-14 * float(base.initial_cost)
    assert float(inex.cost) < 1e-14 * float(inex.initial_cost)
    assert int(inex.pcg_iterations) < int(base.pcg_iterations)


def test_forcing_parity_pgo():
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    g = make_synthetic_pose_graph(num_poses=24, loop_closures=6, seed=2)

    def run(solver_opt):
        option = ProblemOption(
            dtype=np.float64,
            algo_option=AlgoOption(max_iter=40, epsilon1=1e-10,
                                   epsilon2=1e-14),
            solver_option=SolverOption(**solver_opt))
        return solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)

    base = run(TIGHT)
    inex = run(INEXACT)
    # The noiseless pose graph's optimum is cost ~ 0: "same final cost"
    # here means both configurations drive the cost through the same
    # many-orders-of-magnitude reduction (an absolute comparison at
    # ~1e-21 would just compare rounding noise).
    assert float(base.cost) < 1e-16 * float(base.initial_cost)
    assert float(inex.cost) < 1e-16 * float(inex.initial_cost)
    assert int(inex.pcg_iterations) < int(base.pcg_iterations)


def test_forcing_parity_world2():
    from megba_tpu.solve import flat_solve

    s = _bal_problem(seed=3)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    def run(solver_opt):
        option = ProblemOption(
            world_size=2,
            jacobian_mode=JacobianMode.ANALYTICAL,
            algo_option=AlgoOption(max_iter=20, epsilon1=1e-9,
                                   epsilon2=1e-12),
            solver_option=SolverOption(**solver_opt))
        return flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                          s.pt_idx, option)

    base = run(TIGHT)
    inex = run(INEXACT)
    np.testing.assert_allclose(float(inex.cost), float(base.cost),
                               rtol=GAP_RTOL)
    assert int(inex.pcg_iterations) < int(base.pcg_iterations)
    # The sharded warm-start carry is replicated: the resume state comes
    # back well-formed through out_specs=P() (edge-major at the public
    # boundary).
    assert inex.dx_cam is not None and inex.dx_cam.shape == (6, 9)


def test_warm_start_bitwise_disabled_on_reject():
    # A scene observed at the optimum except for a huge trust region and
    # heavy pixel noise rejects its first steps; while EVERY step is
    # rejected the warm-start carry must stay zero, making the solve
    # BITWISE identical to warm_start=False.
    s = make_synthetic_bal(num_cameras=5, num_points=30, obs_per_point=4,
                           seed=7, param_noise=8e-2, pixel_noise=2.0)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    def run(warm, max_iter):
        option = ProblemOption(
            jacobian_mode=JacobianMode.ANALYTICAL,
            algo_option=AlgoOption(max_iter=max_iter,
                                   initial_region=1e14,
                                   epsilon1=1e-12, epsilon2=1e-15),
            solver_option=SolverOption(max_iter=40, tol=1e-10,
                                       refuse_ratio=1e30,
                                       warm_start=warm))
        return jax.jit(
            lambda cams, pts, obs, ci, pi, m: lm_solve(
                f, cams, pts, obs, ci, pi, m, option)
        )(jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
          jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx),
          jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)))

    # Premise check: the scenario really does reject its first steps
    # (Gauss-Newton overshoot on a noisy nonlinear problem).
    probe = run(False, 3)
    rejected = int(np.sum(~np.asarray(probe.trace.accept)[:int(probe.iterations)]))
    assert rejected >= 1, "scenario no longer rejects; pick a new seed"
    n = int(np.argmax(np.asarray(probe.trace.accept))) or 3  # pre-accept span
    cold = run(False, n)
    warm = run(True, n)
    # Bitwise: every rejected step zeroed the carry, so each PCG solve
    # started cold in both runs.
    assert np.array_equal(np.asarray(cold.cameras), np.asarray(warm.cameras))
    assert np.array_equal(np.asarray(cold.points), np.asarray(warm.points))
    assert float(cold.cost) == float(warm.cost)
    assert int(cold.pcg_iterations) == int(warm.pcg_iterations)


def test_forcing_trace_records_eta_and_r0_ratio():
    s = _bal_problem(seed=4)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    res = _solve_bal(s, INEXACT, f)
    n = int(res.iterations)
    eta = np.asarray(res.trace.pcg_eta)[:n]
    r0 = np.asarray(res.trace.pcg_r0_ratio)[:n]
    # eta_k lives in [eta_min, tol] by construction.
    assert np.all(eta >= SolverOption().eta_min - 1e-15)
    assert np.all(eta <= 0.1 + 1e-15)
    # Every rejected step tightens eta for the next iteration (down to
    # the eta_min floor — the reject update is max(eta/4, eta_min)).
    accept = np.asarray(res.trace.accept)[:n]
    eta_min = SolverOption().eta_min
    for k in np.nonzero(~accept)[0]:
        if k + 1 < n:
            assert eta[k + 1] <= max(eta[k] * 0.25, eta_min) + 1e-15
    # Cold start on iteration 0; ratios stay finite and positive after.
    np.testing.assert_allclose(r0[0], 1.0)
    assert np.all(np.isfinite(r0)) and np.all(r0 > 0)
    # Forcing-off solves record the static tolerance instead.
    base = _solve_bal(s, TIGHT, f)
    nb = int(base.iterations)
    np.testing.assert_allclose(np.asarray(base.trace.pcg_eta)[:nb], 1e-12)
    np.testing.assert_allclose(np.asarray(base.trace.pcg_r0_ratio)[:nb], 1.0)


def test_warm_start_relative_tol_anchored_to_rhs():
    # Regression (ISSUE 4 satellite): with a nonzero x0 the relative
    # threshold must scale with <b, M^-1 b>, NOT the initial-guess
    # residual rho0 — anchoring to rho0 makes a good warm start either
    # exit spuriously at 0 iterations (rho0 under the _TINY_RHO floor)
    # or grind to over-converge relative to an already-tiny baseline.
    from megba_tpu.linear_system import build_schur_system, weight_system_inputs
    from megba_tpu.solver.pcg import schur_pcg_solve

    s = make_synthetic_bal(num_cameras=3, num_points=12, seed=5)
    cams, pts = jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T)
    ci, pi = jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx)
    obs = jnp.asarray(s.obs.T)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    r, Jc, Jp = f(cams[:, ci], pts[:, pi], obs)
    r, Jc, Jp = weight_system_inputs(r, Jc, Jp, ci, pi,
                                     jnp.ones(obs.shape[1]))
    system = build_schur_system(r, Jc, Jp, ci, pi, 3, 12)
    region = jnp.asarray(100.0)
    kw = dict(max_iter=300, tol=1e-6, tol_relative=True, refuse_ratio=1e30)

    cold = schur_pcg_solve(system, Jc, Jp, ci, pi, region, **kw)
    assert int(cold.iterations) > 0
    assert float(cold.r0_ratio) == 1.0
    # Warm-started from the cold solution: x0 already satisfies the
    # RHS-anchored threshold, so the solve is a 0-iteration no-op that
    # returns x0 — not a spurious exit (the answer is right) and not a
    # re-grind (iterations stay 0).
    warm = schur_pcg_solve(system, Jc, Jp, ci, pi, region,
                           x0=cold.dx_cam, **kw)
    assert int(warm.iterations) == 0
    assert float(warm.r0_ratio) < 1e-5
    np.testing.assert_allclose(np.asarray(warm.dx_cam),
                               np.asarray(cold.dx_cam), rtol=0, atol=0)
    # A partially-converged warm start must still finish in FEWER
    # iterations than a cold solve to a TIGHT tolerance, and land on
    # the same answer (at tol=1e-10 energy the remaining solution
    # spread is ~1e-5 in norm; looser tolerances would only compare
    # each run's truncation error).
    tight = dict(max_iter=300, tol=1e-10, tol_relative=True,
                 refuse_ratio=1e30)
    cold_t = schur_pcg_solve(system, Jc, Jp, ci, pi, region, **tight)
    rough = schur_pcg_solve(system, Jc, Jp, ci, pi, region,
                            max_iter=max(1, int(cold_t.iterations) // 2),
                            tol=1e-10, tol_relative=True,
                            refuse_ratio=1e30)
    resumed = schur_pcg_solve(system, Jc, Jp, ci, pi, region,
                              x0=rough.dx_cam, **tight)
    assert int(resumed.iterations) < int(cold_t.iterations)
    scale = float(jnp.max(jnp.abs(cold_t.dx_cam)))
    np.testing.assert_allclose(np.asarray(resumed.dx_cam),
                               np.asarray(cold_t.dx_cam),
                               atol=1e-3 * scale)
    # Zero RHS + nonzero x0: the _TINY_RHO floor still applies to the
    # b-anchored threshold, so the solve stays finite (and drives the
    # residual of the spurious x0 down rather than exiting on it).
    import dataclasses as _dc

    zsys = _dc.replace(system, g_cam=jnp.zeros_like(system.g_cam),
                       g_pt=jnp.zeros_like(system.g_pt))
    zero = schur_pcg_solve(zsys, Jc, Jp, ci, pi, region,
                           x0=cold.dx_cam, **kw)
    assert np.all(np.isfinite(np.asarray(zero.dx_cam)))
    # ...and the fully-zero problem still exits immediately.
    zero_cold = schur_pcg_solve(zsys, Jc, Jp, ci, pi, region, **kw)
    assert int(zero_cold.iterations) == 0


def test_checkpointed_warm_start_resumes_across_chunks(tmp_path):
    # The chunked driver threads LMResult.dx_cam back in as initial_dx:
    # a chunked forcing+warm-start solve must land on the straight
    # solve's optimum (trust region, eta restart and warm-start carry
    # all ride the resume state or reconverge within the chunk).
    from megba_tpu.algo.checkpointed import solve_checkpointed
    from megba_tpu.solve import flat_solve

    s = _bal_problem(seed=6)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    option = ProblemOption(
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=16, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(**INEXACT))
    straight = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                          s.pt_idx, option)
    ck = str(tmp_path / "warm.npz")
    chunked = solve_checkpointed(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
        checkpoint_path=ck, checkpoint_every=4)
    np.testing.assert_allclose(float(chunked.cost), float(straight.cost),
                               rtol=1e-5)
    # The snapshot carries the warm-start resume state.
    from megba_tpu.utils.checkpoint import load_state

    st = load_state(ck)
    assert "extra_dx" in st and st["extra_dx"].shape == (6, 9)
