"""g2o-style Problem/Vertex/Edge facade tests (reference user-API parity)."""

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu import (
    BaseEdge,
    BaseProblem,
    CameraVertex,
    ComputeKind,
    JacobianMode,
    PointVertex,
    ProblemOption,
)
from megba_tpu.common import AlgoOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal


def build_problem(option=None, seed=0, fix_first_camera=False):
    s = make_synthetic_bal(num_cameras=5, num_points=30, obs_per_point=3,
                           seed=seed, param_noise=4e-2, pixel_noise=0.2)
    pb = BaseProblem(option or ProblemOption(
        algo_option=AlgoOption(max_iter=20, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-13, refuse_ratio=1e30)))
    cams, pts = [], []
    for i, est in enumerate(s.cameras0):
        v = CameraVertex(est, fixed=(fix_first_camera and i == 0))
        pb.append_vertex(i, v)
        cams.append(v)
    for j, est in enumerate(s.points0):
        v = PointVertex(est)
        pb.append_vertex(1000 + j, v)
        pts.append(v)
    for c, p, uv in zip(s.cam_idx, s.pt_idx, s.obs):
        pb.append_edge(BaseEdge([cams[c], pts[p]], measurement=uv))
    return s, pb, cams, pts


def test_solve_writes_back():
    s, pb, cams, pts = build_problem()
    before = cams[1].estimation.copy()
    res = pb.solve()
    assert float(res.cost) < float(res.initial_cost) * 1e-3
    assert not np.allclose(cams[1].estimation, before)  # written back
    # get_vertex returns the same (updated) object.
    assert pb.get_vertex(1) is cams[1]


def test_fixed_vertex_round_trip():
    s, pb, cams, pts = build_problem(fix_first_camera=True)
    frozen = cams[0].estimation.copy()
    pb.solve()
    np.testing.assert_array_equal(cams[0].estimation, frozen)


def test_erase_vertex_removes_edges():
    s, pb, cams, pts = build_problem()
    n_edges = len(pb._edges)
    touching = sum(1 for e in pb._edges if e.vertices[1] is pts[0])
    pb.erase_vertex(1000)
    assert len(pb._edges) == n_edges - touching
    with pytest.raises(KeyError):
        pb.get_vertex(1000)


def test_heterogeneous_edges_rejected():
    class OtherEdge(BaseEdge):
        pass

    s, pb, cams, pts = build_problem()
    with pytest.raises(TypeError, match="heterogeneous"):
        pb.append_edge(OtherEdge([cams[0], pts[0]], measurement=np.zeros(2)))


def test_wrong_vertex_kinds_rejected():
    pb = BaseProblem()
    c = CameraVertex(np.zeros(9))
    pb.append_vertex(0, c)
    pb.append_vertex(1, CameraVertex(np.zeros(9)))
    with pytest.raises(NotImplementedError):
        pb.append_edge(BaseEdge([c, pb.get_vertex(1)], measurement=np.zeros(2)))


def test_custom_forward_edge():
    # A user edge overriding forward() with plain jnp math must solve via
    # autodiff and agree with the built-in BAL edge.
    class MyBALEdge(BaseEdge):
        def forward(self):
            camera = self.vertex_estimation(0)
            point = self.vertex_estimation(1)
            w, t = camera[0:3], camera[3:6]
            f, k1, k2 = camera[6], camera[7], camera[8]
            from megba_tpu.ops import geo
            P = geo.angle_axis_rotate_point(w, point) + t
            p = -P[0:2] / P[2]
            n = jnp.dot(p, p)
            return f * (1.0 + k1 * n + k2 * n * n) * p - self.get_measurement()

    s = make_synthetic_bal(num_cameras=4, num_points=20, obs_per_point=3,
                           seed=2, param_noise=3e-2, pixel_noise=0.2)

    def solve_with(edge_cls):
        pb = BaseProblem(ProblemOption(
            algo_option=AlgoOption(max_iter=15, epsilon1=1e-9, epsilon2=1e-12),
            solver_option=SolverOption(max_iter=100, tol=1e-13, refuse_ratio=1e30)))
        cams = [CameraVertex(e) for e in s.cameras0]
        pts = [PointVertex(e) for e in s.points0]
        for i, v in enumerate(cams):
            pb.append_vertex(i, v)
        for j, v in enumerate(pts):
            pb.append_vertex(1000 + j, v)
        for c, p, uv in zip(s.cam_idx, s.pt_idx, s.obs):
            pb.append_edge(edge_cls([cams[c], pts[p]], measurement=uv))
        return pb.solve()

    res_custom = solve_with(MyBALEdge)
    res_builtin = solve_with(BaseEdge)
    np.testing.assert_allclose(float(res_custom.cost), float(res_builtin.cost), rtol=1e-8)


def test_information_matrix_weighting():
    # Doubling the information of every edge scales the cost by 2 but
    # leaves the minimiser unchanged.
    s, pb1, *_ = build_problem(seed=4)
    res1 = pb1.solve()

    s2 = make_synthetic_bal(num_cameras=5, num_points=30, obs_per_point=3,
                            seed=4, param_noise=4e-2, pixel_noise=0.2)
    pb2 = BaseProblem(ProblemOption(
        algo_option=AlgoOption(max_iter=20, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-13, refuse_ratio=1e30)))
    cams = [CameraVertex(e) for e in s2.cameras0]
    pts = [PointVertex(e) for e in s2.points0]
    for i, v in enumerate(cams):
        pb2.append_vertex(i, v)
    for j, v in enumerate(pts):
        pb2.append_vertex(1000 + j, v)
    for c, p, uv in zip(s2.cam_idx, s2.pt_idx, s2.obs):
        pb2.append_edge(BaseEdge([cams[c], pts[p]], measurement=uv,
                                 information=2.0 * np.eye(2)))
    res2 = pb2.solve()
    np.testing.assert_allclose(float(res2.cost), 2.0 * float(res1.cost), rtol=1e-6)


def test_world_size_two_through_api():
    from tests.conftest import cpu_devices  # ensure devices exist
    assert len(cpu_devices(2)) == 2
    opt = ProblemOption(
        world_size=2,
        algo_option=AlgoOption(max_iter=15, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-13, refuse_ratio=1e30))
    s, pb, cams, pts = build_problem(option=opt, seed=6)
    res = pb.solve()
    assert float(res.cost) < float(res.initial_cost) * 1e-2


def test_pose_graph_facade_matches_direct_solve():
    """PoseVertex + BetweenEdge through BaseProblem == solve_pgo.

    The g2o-style object API covers the pose-graph family too (a family
    the reference's camera/landmark-typed edges cannot express).
    """
    from megba_tpu.common import AlgoOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo
    from megba_tpu.problem import BetweenEdge, PoseVertex

    g = make_synthetic_pose_graph(num_poses=14, loop_closures=3,
                                  drift_noise=0.05, seed=9)
    option = ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=20, epsilon1=1e-12,
                               epsilon2=1e-15),
        solver_option=SolverOption(max_iter=100, tol=1e-14,
                                   refuse_ratio=1e30))

    pb = BaseProblem(option)
    verts = []
    for k, p in enumerate(g.poses0):
        v = PoseVertex(p, fixed=(k == 0))
        verts.append(v)
        pb.append_vertex(k, v)
    for a, b, m in zip(g.edge_i, g.edge_j, g.meas):
        pb.append_edge(BetweenEdge([verts[a], verts[b]], measurement=m))

    result = pb.solve()
    direct = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)
    np.testing.assert_allclose(float(result.cost), float(direct.cost),
                               rtol=1e-9, atol=1e-18)
    # Write-back: vertices hold the solution; the anchor did not move.
    np.testing.assert_allclose(
        np.stack([v.estimation for v in verts]),
        np.asarray(direct.poses), atol=1e-12)
    np.testing.assert_array_equal(verts[0].estimation, g.poses0[0])

    # Weighted edges route through the same Cholesky convention as BA.
    pb2 = BaseProblem(option)
    verts2 = [PoseVertex(p, fixed=(k == 0))
              for k, p in enumerate(g.poses0)]
    for k, v in enumerate(verts2):
        pb2.append_vertex(k, v)
    for a, b, m in zip(g.edge_i, g.edge_j, g.meas):
        pb2.append_edge(BetweenEdge([verts2[a], verts2[b]], measurement=m,
                                    information=4.0 * np.eye(6)))
    r2 = pb2.solve()
    r2_direct = solve_pgo(
        g.poses0, g.edge_i, g.edge_j, g.meas, option,
        sqrt_info=np.tile(2.0 * np.eye(6), (len(g.edge_i), 1, 1)))
    np.testing.assert_allclose(float(r2.cost), float(r2_direct.cost),
                               rtol=1e-9, atol=1e-18)


def test_pose_graph_facade_validation():
    from megba_tpu.problem import BetweenEdge, PoseVertex

    pb = BaseProblem(ProblemOption())
    v0 = PoseVertex(np.zeros(6))
    v1 = PoseVertex(np.ones(6))
    pb.append_vertex(0, v0)
    pb.append_vertex(1, v1)
    # A plain BaseEdge over poses is rejected (its forward is the BAL
    # reprojection model).
    with pytest.raises(TypeError, match="BetweenEdge"):
        pb.append_edge(BaseEdge([v0, v1], measurement=np.zeros(6)))
    # Wrong parameter count caught at construction.
    with pytest.raises(ValueError, match="6 parameters"):
        PoseVertex(np.zeros(7))


def test_between_edge_guards():
    from megba_tpu.problem import BetweenEdge, PoseVertex

    # Measurement/information shape caught at construction.
    p0, p1 = PoseVertex(np.zeros(6)), PoseVertex(np.ones(6))
    with pytest.raises(ValueError, match="6 values"):
        BetweenEdge([p0, p1], measurement=np.zeros(3))
    with pytest.raises(ValueError, match="6x6"):
        BetweenEdge([p0, p1], measurement=np.zeros(6),
                    information=np.eye(3))

    # BetweenEdge over non-pose vertices is rejected at append.
    pb = BaseProblem(ProblemOption())
    cam = CameraVertex(np.zeros(9))
    pt = PointVertex(np.zeros(3))
    pb.append_vertex(0, cam)
    pb.append_vertex(1, pt)
    with pytest.raises(TypeError, match="two PoseVertex"):
        pb.append_edge(BetweenEdge([cam, pt], measurement=np.zeros(6)))

    # PSD (singular) information factors cleanly through the facade.
    from megba_tpu.models.pgo import make_synthetic_pose_graph

    g = make_synthetic_pose_graph(num_poses=8, loop_closures=2, seed=4)
    pb2 = BaseProblem(ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=10, epsilon1=1e-12,
                               epsilon2=1e-15),
        solver_option=SolverOption(max_iter=60, tol=1e-12,
                                   refuse_ratio=1e30)))
    verts = [PoseVertex(p, fixed=(k == 0)) for k, p in enumerate(g.poses0)]
    for k, v in enumerate(verts):
        pb2.append_vertex(k, v)
    info_psd = np.diag([1.0, 1.0, 1.0, 1.0, 1.0, 0.0])
    for a, b, m in zip(g.edge_i, g.edge_j, g.meas):
        pb2.append_edge(BetweenEdge([verts[a], verts[b]], measurement=m,
                                    information=info_psd))
    res = pb2.solve()
    assert np.isfinite(float(res.cost))
