"""Elastic kill-resume: a REAL multi-process worker death, end to end.

The multi-process analogue of tests/test_killresume.py's bitwise
kill-resume proof: two rank processes join one elastic gloo cluster
(client-only; the coordination service lives in a sacrificial
rendezvous process) and run ONE world-2 checkpointed BA solve.  The
harness SIGKILLs rank 1 the moment the first world-2 snapshot lands —
mid-chunk, no atexit, no flush — and rank 0 must then, ON ITS OWN:

1. surface the loss as a typed `WorkerLost` within the watchdog budget
   (the ELASTIC-DETECT line carries the measured time-to-detection);
2. tear down the distributed runtime and resume at world 1 from the
   latest schema-v3 snapshot (`resume_elastic`);
3. run to completion and EXIT 0 — the no-wedge contract is enforced by
   the harness itself (a survivor still running past the grace is a
   TimeoutError).

The result must match an uninterrupted single-process world-2 run of
the byte-identical problem at the sharded-parity tolerance: rtol 1e-6
on final cost AND parameters, equal SolveStatus.  (A 2-process world-2
solve matches the single-process world-2 solve — same mesh size, same
program, same collectives — per test_multihost.py's parity lane, so
the single-process run is a valid clean reference.)
"""

import importlib.util
import os
import re
import socket
import sys

import numpy as np
import pytest

from megba_tpu.parallel.multihost import (
    cpu_cross_process_collectives_available,
)
from megba_tpu.robustness.harness import run_world_until_snapshot_then_kill
from megba_tpu.utils.checkpoint import load_state

needs_cpu_collectives = pytest.mark.skipif(
    not cpu_cross_process_collectives_available(),
    reason="jaxlib CPU client lacks gloo TCP collectives: multiprocess "
           "computations aren't implemented on the plain CPU backend")

_WORKER = os.path.join(os.path.dirname(__file__), "_elastic_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _load_worker_module():
    spec = importlib.util.spec_from_file_location("_elastic_worker", _WORKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
@needs_cpu_collectives
def test_world2_sigkill_rank1_detect_shrink_resume_parity(tmp_path,
                                                          retrace_sentinel):
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hb_dir = str(tmp_path / "hb")
    ck0 = str(tmp_path / "ck.r0.npz")
    ck1 = str(tmp_path / "ck.r1.npz")
    out0 = str(tmp_path / "result.npz")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each worker pins its own single device
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def worker_argv(rank: int, ckpt: str, out: str):
        return [sys.executable, _WORKER, str(rank), str(port), "2",
                ckpt, out, "tiny", hb_dir]

    rendezvous = [sys.executable, "-m", "megba_tpu.parallel.multihost",
                  "--serve", str(port), "2"]
    outcome = run_world_until_snapshot_then_kill(
        [worker_argv(0, ck0, out0), worker_argv(1, ck1, "-")],
        ck0, kill_rank=1, rendezvous_argv=rendezvous,
        timeout=600.0, survivor_timeout=600.0, env=env)

    # Rank 1 died by SIGKILL; rank 0 detected, resumed, and exited 0
    # on its own (the harness's survivor wait IS the no-wedge gate).
    assert outcome.returncodes[1] < 0, outcome.outputs[1]
    assert outcome.returncodes[0] == 0, outcome.outputs[0]
    out = outcome.outputs[0]

    # Typed detection within the watchdog budget, latency measured.
    m = re.search(r"ELASTIC-DETECT kind=(\w+) latency=([0-9.]+) "
                  r"budget=([0-9.]+)", out)
    assert m, f"rank 0 printed no detection line:\n{out}"
    kind, latency, budget = m.group(1), float(m.group(2)), float(m.group(3))
    assert kind == "worker_lost", out
    assert latency <= budget, (latency, budget)
    assert re.search(r"ELASTIC-RESUME world=1", out), out

    # The surviving snapshot chain: written at world 2 before the kill
    # (the recovery line), finished at world 1 after the shrink.
    final = load_state(ck0)
    assert int(final["world_size"]) == 1
    ew = _load_worker_module()

    # Parity vs the uninterrupted world-2 run of the byte-identical
    # problem (single-process, 2 virtual devices — same mesh size and
    # program as the 2-process world).
    from megba_tpu.algo.checkpointed import solve_checkpointed
    from megba_tpu.common import JacobianMode
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    s, option = ew.build_problem("tiny", 2)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    ref = solve_checkpointed(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
        checkpoint_path=str(tmp_path / "clean.npz"),
        checkpoint_every=ew.CHECKPOINT_EVERY, use_tiled=False)

    res = dict(np.load(out0))
    assert str(res["detect_kind"]) == "worker_lost"
    assert int(final["iteration"]) == int(res["iterations"])
    assert int(res["status"]) == int(ref.status)
    assert int(res["iterations"]) == int(ref.iterations)
    np.testing.assert_allclose(float(res["cost"]), float(ref.cost),
                               rtol=1e-6)
    np.testing.assert_allclose(res["cameras"], np.asarray(ref.cameras),
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(res["points"], np.asarray(ref.points),
                               rtol=1e-6, atol=1e-9)


@pytest.mark.slow
def test_shrink_world_resume_in_process_parity(tmp_path, retrace_sentinel):
    """The shrink arithmetic without processes: run world-2 chunks
    (virtual devices), stop at the snapshot, resume_elastic at world 1,
    and match the uninterrupted world-2 run.  Also pins that the
    resumed lowering compiles at most one NEW program (a fresh shape
    class, certified by the retrace sentinel fixture at teardown)."""
    import dataclasses

    from megba_tpu.algo.checkpointed import solve_checkpointed
    from megba_tpu.common import JacobianMode
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.robustness.elastic import resume_elastic

    ew = _load_worker_module()
    s, option = ew.build_problem("tiny", 2)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    args = (f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx)

    clean = solve_checkpointed(
        *args, option, checkpoint_path=str(tmp_path / "clean.npz"),
        checkpoint_every=ew.CHECKPOINT_EVERY, use_tiled=False)

    # Interrupted run: first chunk at world 2, then "the world shrank".
    ck = str(tmp_path / "elastic.npz")
    short = dataclasses.replace(option, algo_option=dataclasses.replace(
        option.algo_option, max_iter=ew.CHECKPOINT_EVERY))
    solve_checkpointed(*args, short, checkpoint_path=ck,
                       checkpoint_every=ew.CHECKPOINT_EVERY,
                       use_tiled=False)
    assert int(load_state(ck)["world_size"]) == 2
    res = resume_elastic(*args, option, ck, world_size=1,
                         checkpoint_every=ew.CHECKPOINT_EVERY,
                         use_tiled=False)
    assert int(load_state(ck)["world_size"]) == 1
    assert int(res.status) == int(clean.status)
    np.testing.assert_allclose(float(res.cost), float(clean.cost),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(res.cameras),
                               np.asarray(clean.cameras),
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(np.asarray(res.points),
                               np.asarray(clean.points),
                               rtol=1e-6, atol=1e-9)
