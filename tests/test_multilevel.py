"""Multilevel Schur preconditioning on real-graph structure (ISSUE 11).

Contracts pinned here:

- Locality scenes: `make_synthetic_bal(locality=None)` is BYTE-
  identical to the pre-locality generator (pinned digests), and the
  ring/grid modes produce the banded camera co-observation structure
  the coarse-space preconditioners exist for.
- Smoothed aggregation: the smoothed prolongator's Galerkin operator
  and coupling are EXACTLY Πᵀ S_d Π and S_d Π with Π = Rᵀ − ω D⁻¹ S_d Rᵀ
  (dense parity, f64), verified against the plain-aggregation operators
  they extend, and the smoothed cycle matches the explicit formula.
- Multilevel hierarchy: the L-level cycle materialises to a symmetric
  (~1e-14 rel, f64) positive-definite M⁻¹; depth-2 MULTILEVEL is
  bitwise the TWO_LEVEL apply; the LM-level solve reaches the
  block-Jacobi optimum (rtol 1e-6) in strictly fewer PCG iterations on
  a locality scene; world-2 matches single-device iteration counts.
- Per-level fallback: the bit-field encode/decode round-trips at L>2,
  a poisoned build truncates the cycle to the base apply bitwise with
  the per-level bits set, and the report decoder sums per-level totals.
- Plans: the recursive aggregation shrinks monotonically, composes to
  a partition, and every aggregation knob (target, coarsen_factor,
  max_levels, smooth_omega) is part of the plan-cache fingerprint.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    ComputeKind,
    JacobianMode,
    PrecondKind,
    PreconditionerKind,
    ProblemOption,
    SolverOption,
    validate_options,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.linear_system import build_schur_system, weight_system_inputs
from megba_tpu.linear_system.builder import damp_blocks
from megba_tpu.core.fm import block_inv_fm, coupling_rows, damp_rows_fm
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.ops.segtiles import (
    build_cluster_plan,
    build_multilevel_plan,
    cached_cluster_plan,
    cached_multilevel_plan,
    device_cluster_plan,
    device_multilevel_plan,
)
from megba_tpu.solve import flat_solve
from megba_tpu.solver.precond import (
    FALLBACK_BLOCK_RADIX,
    block_inv,
    build_two_level_coarse,
    cam_block_matvec,
    decode_precond_fallback,
    decode_precond_fallback_levels,
    encode_precond_fallback,
    make_schur_preconditioner,
    multilevel_cycle,
    build_multilevel_coarse,
    two_level_cycle,
)

CD, PD = 9, 3


# ------------------------------------------------------- locality scenes


def _scene_digest(s) -> str:
    h = hashlib.blake2b(digest_size=16)
    for a in (s.cameras_gt, s.points_gt, s.cameras0, s.points0, s.obs,
              s.cam_idx, s.pt_idx):
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def test_locality_none_is_byte_identical_to_pre_locality_generator():
    # Digests recorded from the generator BEFORE the locality mode
    # existed (this PR's baseline): the default path must reproduce
    # those scenes byte-for-byte, degeneracy knobs included.
    assert _scene_digest(make_synthetic_bal(
        num_cameras=6, num_points=40, obs_per_point=3, seed=0)) == \
        "e8331c6c6292715d281a0e9de73beeee"
    assert _scene_digest(make_synthetic_bal(
        num_cameras=10, num_points=60, obs_per_point=3.5, seed=7,
        param_noise=5e-2, pixel_noise=0.3, dtype=np.float32)) == \
        "275943270b53634fba02bdc95f29568a"
    assert _scene_digest(make_synthetic_bal(
        num_cameras=5, num_points=30, obs_per_point=2.5, seed=3,
        n_orphan_points=2, n_behind_camera=1, n_disconnect=1)) == \
        "dc1e3da0e744aad58c2b766cf8422d3c"


@pytest.mark.parametrize("mode", ["ring", "grid"])
def test_locality_modes_are_banded_and_well_formed(mode):
    Nc, Np = 32, 400
    s = make_synthetic_bal(num_cameras=Nc, num_points=Np, obs_per_point=4,
                           seed=0, locality=mode)
    # Every camera observes something; edge budget matches the base
    # generator's obs_per_point accounting (plus missing-camera fixups).
    assert set(np.unique(s.cam_idx)) == set(range(Nc))
    assert s.obs.shape[0] >= Np * 4
    # Deterministic in the seed.
    s2 = make_synthetic_bal(num_cameras=Nc, num_points=Np, obs_per_point=4,
                            seed=0, locality=mode)
    assert _scene_digest(s) == _scene_digest(s2)
    # Windowed visibility => banded co-observation: no point is shared
    # by cameras farther apart than a small neighbourhood (ring metric
    # for the ring; for the grid just assert the pair count is far
    # below the expander's near-complete co-observation graph).
    pairs = set()
    by_pt = {}
    for c, p in zip(s.cam_idx, s.pt_idx):
        by_pt.setdefault(int(p), []).append(int(c))
    for cams in by_pt.values():
        for a in cams:
            for b in cams:
                if a < b:
                    pairs.add((a, b))
    if mode == "ring":
        max_sep = max(min(abs(a - b), Nc - abs(a - b)) for a, b in pairs)
        assert max_sep <= 6, max_sep  # window of 4-nearest on 32 anchors
    assert len(pairs) < 0.35 * Nc * (Nc - 1) / 2, len(pairs)
    # Cheirality: every observation sees its point IN FRONT (the
    # locality layout must not have broken the BAL z<0 convention).
    from megba_tpu.io.synthetic import project_batch_depth

    _, z = project_batch_depth(s.cameras_gt[s.cam_idx],
                               s.points_gt[s.pt_idx])
    assert float(z.max()) < 0


def test_locality_composes_with_degeneracy_knobs():
    s = make_synthetic_bal(num_cameras=8, num_points=50, obs_per_point=3,
                           seed=1, locality="ring", n_orphan_points=3,
                           n_behind_camera=2)
    base = make_synthetic_bal(num_cameras=8, num_points=50, obs_per_point=3,
                              seed=1, locality="ring")
    assert s.points_gt.shape[0] == base.points_gt.shape[0] + 5
    with pytest.raises(ValueError, match="locality"):
        make_synthetic_bal(num_cameras=4, num_points=8, locality="torus")


# ------------------------------------------------ dense reference helpers


def _system(num_cameras=7, num_points=40, seed=2, locality=None):
    s = make_synthetic_bal(num_cameras=num_cameras, num_points=num_points,
                           obs_per_point=4, seed=seed, locality=locality)
    cams, pts = jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T)
    ci, pi = jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx)
    obs = jnp.asarray(s.obs.T)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    r, Jc, Jp = f(cams[:, ci], pts[:, pi], obs)
    r, Jc, Jp = weight_system_inputs(r, Jc, Jp, ci, pi,
                                     jnp.ones(obs.shape[1]))
    system = build_schur_system(r, Jc, Jp, ci, pi, num_cameras, num_points)
    return s, system, Jc, Jp, ci, pi


def _dense_schur(s, system, Jc, Jp, region):
    Nc = system.Hpp.shape[0]
    Np = system.Hll.shape[1]
    od = Jc.shape[0] // CD
    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_rows_fm(system.Hll, region)
    Hinv = np.asarray(block_inv_fm(Hll_d))
    W = np.asarray(coupling_rows(Jc, Jp, od))
    S = np.zeros((Nc * CD, Nc * CD))
    for i in range(Nc):
        S[i * CD:(i + 1) * CD, i * CD:(i + 1) * CD] = np.asarray(Hpp_d[i])
    Hpl = np.zeros((Nc * CD, Np * PD))
    for e in range(len(s.cam_idx)):
        c, p = int(s.cam_idx[e]), int(s.pt_idx[e])
        Hpl[c * CD:(c + 1) * CD, p * PD:(p + 1) * PD] += (
            W[:, e].reshape(CD, PD))
    Hlli = np.zeros((Np * PD, Np * PD))
    for p in range(Np):
        Hlli[p * PD:(p + 1) * PD, p * PD:(p + 1) * PD] = (
            Hinv[:, p].reshape(PD, PD))
    return (S - Hpl @ Hlli @ Hpl.T, Hpp_d,
            jnp.asarray(block_inv_fm(Hll_d)), W)


def _materialize(apply_fn, n_cams):
    cols = []
    for e in np.eye(n_cams * CD):
        rfm = jnp.asarray(e.reshape(n_cams, CD).T)
        cols.append(np.asarray(apply_fn(rfm)).T.reshape(-1))
    return np.stack(cols, axis=1)


def _dense_R(cluster, Nc, C):
    R = np.zeros((C * CD, Nc * CD))
    for n in range(Nc):
        I = cluster[n]
        R[I * CD:(I + 1) * CD, n * CD:(n + 1) * CD] = np.eye(CD)
    return R


# ------------------------------------------- smoothed-aggregation parity


def test_smoothed_galerkin_and_coupling_dense_parity():
    omega = 0.6
    s, system, Jc, Jp, ci, pi = _system()
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(50.0)
    S, Hpp_d, Hll_inv, W = _dense_schur(s, system, Jc, Jp, region)
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, Nc, system.Hll.shape[1])
    dplan = device_cluster_plan(plan)
    C = plan.num_clusters
    coarse = build_two_level_coarse(
        Hpp_d, Hll_inv, jnp.asarray(W), Jc, Jp, dplan,
        ComputeKind.EXPLICIT, smooth_omega=omega, cam_idx=ci, pt_idx=pi)
    assert bool(coarse.ok)
    assert coarse.Y is not None and coarse.omega == omega

    # Explicit smoothed prolongator Π = Rᵀ − ω D⁻¹ S Rᵀ vs the PLAIN-
    # aggregation operators it extends.
    R = _dense_R(plan.cluster, Nc, C)
    D_inv = np.zeros((Nc * CD, Nc * CD))
    binv = np.asarray(block_inv(Hpp_d))
    for n in range(Nc):
        D_inv[n * CD:(n + 1) * CD, n * CD:(n + 1) * CD] = binv[n]
    Pi = R.T - omega * D_inv @ S @ R.T
    atol = 1e-9 * np.abs(S).max()
    # Y = D⁻¹ S Rᵀ
    Yd = np.asarray(coarse.Y)
    Y_impl = np.zeros((Nc * CD, C * CD))
    for a in range(CD):
        for n in range(Nc):
            Y_impl[n * CD + a, :] = Yd[a, n].reshape(-1)
    np.testing.assert_allclose(Y_impl, D_inv @ S @ R.T, atol=atol)
    # G = S Π (the column-blocked S·Y pass, exactly)
    Gd = np.asarray(coarse.G)
    G_impl = np.zeros((Nc * CD, C * CD))
    for a in range(CD):
        for n in range(Nc):
            G_impl[n * CD + a, :] = Gd[a, n].reshape(-1)
    np.testing.assert_allclose(G_impl, S @ Pi, atol=atol)
    # A_c = Πᵀ S Π
    np.testing.assert_allclose(np.asarray(coarse.coarse_matrix),
                               Pi.T @ S @ Pi, atol=atol)


def test_smoothed_cycle_matches_explicit_formula_and_is_spd():
    omega = 0.6
    s, system, Jc, Jp, ci, pi = _system()
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(50.0)
    S, Hpp_d, Hll_inv, W = _dense_schur(s, system, Jc, Jp, region)
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, Nc, system.Hll.shape[1])
    coarse = build_two_level_coarse(
        Hpp_d, Hll_inv, jnp.asarray(W), Jc, Jp,
        device_cluster_plan(plan), ComputeKind.EXPLICIT,
        smooth_omega=omega, cam_idx=ci, pt_idx=pi)
    C = plan.num_clusters
    binv = block_inv(Hpp_d)
    base = lambda x: cam_block_matvec(binv, x)
    M_impl = _materialize(lambda r: two_level_cycle(coarse, base, r), Nc)

    R = _dense_R(plan.cluster, Nc, C)
    D_inv = np.zeros((Nc * CD, Nc * CD))
    for n in range(Nc):
        D_inv[n * CD:(n + 1) * CD,
              n * CD:(n + 1) * CD] = np.asarray(binv[n])
    Pi = R.T - omega * D_inv @ S @ R.T
    Ac = Pi.T @ S @ Pi
    lam, Q = np.linalg.eigh(0.5 * (Ac + Ac.T))
    keep = lam > 1e-5 * lam.max()
    Aplus = (Q[:, keep] / lam[keep]) @ Q[:, keep].T
    P = np.eye(Nc * CD) - S @ Pi @ Aplus @ Pi.T
    M_ref = Pi @ Aplus @ Pi.T + P.T @ D_inv @ P
    np.testing.assert_allclose(M_impl, M_ref,
                               atol=1e-9 * np.abs(M_ref).max())
    sym = np.abs(M_impl - M_impl.T).max() / np.abs(M_impl).max()
    assert sym < 1e-12
    assert np.linalg.eigvalsh(0.5 * (M_impl + M_impl.T)).min() > 0


# ------------------------------------------------- multilevel hierarchy


def test_multilevel_cycle_is_symmetric_spd_at_depth_3plus():
    s, system, Jc, Jp, ci, pi = _system(num_cameras=24, num_points=160,
                                        locality="ring")
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(100.0)
    S, Hpp_d, Hll_inv, W = _dense_schur(s, system, Jc, Jp, region)
    mp = build_multilevel_plan(s.cam_idx, s.pt_idx, Nc,
                               system.Hll.shape[1], coarsen_factor=2.0,
                               max_levels=4)
    assert len(mp.level_sizes) >= 2  # genuinely past two levels
    for omega in (0.0, 0.5):
        apply_fn, code = make_schur_preconditioner(
            PrecondKind.MULTILEVEL, PreconditionerKind.HPP, Hpp_d,
            Hll_inv, jnp.asarray(W), Jc, Jp, ci, pi, Nc,
            ComputeKind.EXPLICIT, None, False,
            cluster_plan=device_multilevel_plan(mp), smooth_omega=omega)
        M = _materialize(apply_fn, Nc)
        sym = np.abs(M - M.T).max() / np.abs(M).max()
        assert sym < 1e-12, (omega, sym)
        ev = np.linalg.eigvalsh(0.5 * (M + M.T))
        assert ev.min() > 0, (omega, ev.min())
        assert int(code) == 0
        # The hierarchy must actually help: preconditioned condition
        # number strictly below plain block-Jacobi's.
        Minv_j = _materialize(
            lambda r: cam_block_matvec(block_inv(Hpp_d), r), Nc)

        def cond_of(Mx):
            evs = np.linalg.eigvals(Mx @ S).real
            evs = evs[evs > 1e-9 * evs.max()]
            return evs.max() / evs.min()

        assert cond_of(M) < 0.5 * cond_of(Minv_j)


def test_multilevel_depth2_is_bitwise_the_two_level_apply():
    s, system, Jc, Jp, ci, pi = _system()
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(80.0)
    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_inv = block_inv_fm(damp_rows_fm(system.Hll, region))
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, Nc, system.Hll.shape[1])
    mp = build_multilevel_plan(s.cam_idx, s.pt_idx, Nc,
                               system.Hll.shape[1], max_levels=2)
    assert len(mp.assign) == 0 and mp.level_sizes == (plan.num_clusters,)
    two, code2 = make_schur_preconditioner(
        PrecondKind.TWO_LEVEL, PreconditionerKind.HPP, Hpp_d, Hll_inv,
        None, Jc, Jp, ci, pi, Nc, ComputeKind.IMPLICIT, None, False,
        cluster_plan=device_cluster_plan(plan))
    multi, codem = make_schur_preconditioner(
        PrecondKind.MULTILEVEL, PreconditionerKind.HPP, Hpp_d, Hll_inv,
        None, Jc, Jp, ci, pi, Nc, ComputeKind.IMPLICIT, None, False,
        cluster_plan=device_multilevel_plan(mp))
    rng = np.random.default_rng(0)
    r = jnp.asarray(rng.standard_normal((CD, Nc)))
    np.testing.assert_array_equal(np.asarray(two(r)), np.asarray(multi(r)))
    assert int(code2) == int(codem) == 0


def test_multilevel_poisoned_build_truncates_to_base_apply_bitwise():
    s, system, Jc, Jp, ci, pi = _system(num_cameras=24, num_points=160,
                                        locality="ring")
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(80.0)
    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_inv = block_inv_fm(damp_rows_fm(system.Hll, region))
    mp = build_multilevel_plan(s.cam_idx, s.pt_idx, Nc,
                               system.Hll.shape[1], coarsen_factor=2.0,
                               max_levels=4)
    n_coarse = len(mp.level_sizes)
    assert n_coarse >= 2
    Hpp_bad = Hpp_d.at[0, 0, 0].set(jnp.nan)
    apply_bad, code = make_schur_preconditioner(
        PrecondKind.MULTILEVEL, PreconditionerKind.HPP, Hpp_bad, Hll_inv,
        None, Jc, Jp, ci, pi, Nc, ComputeKind.IMPLICIT, None, False,
        cluster_plan=device_multilevel_plan(mp))
    # Level 1's operator is NaN => every level truncates (ancestor
    # gating), so the bit-field carries one bit per planned level.
    levels = decode_precond_fallback_levels(int(code))
    assert levels == [True] * n_coarse, levels
    assert decode_precond_fallback(int(code))["block"] == 0
    # And the apply IS the base block-Jacobi apply, bitwise (on the
    # finite blocks; block 0's NaN inverse is NaN both ways).
    rng = np.random.default_rng(1)
    r = jnp.asarray(rng.standard_normal((CD, Nc)))
    want = cam_block_matvec(block_inv(Hpp_bad), r)
    np.testing.assert_array_equal(np.asarray(apply_bad(r))[:, 1:],
                                  np.asarray(want)[:, 1:])


# --------------------------------------------- per-level fallback codes


def test_fallback_bitfield_round_trips_beyond_two_levels():
    for block, bits in ((0, 0), (3, 0b1), (0, 0b101), (37, 0b111),
                        (65535, 0b1000)):
        code = encode_precond_fallback(jnp.int32(block), jnp.int32(bits))
        got = decode_precond_fallback(int(code))
        assert got == {"block": block, "coarse": bits}
        levels = decode_precond_fallback_levels(int(code))
        assert levels == [bool(bits >> i & 1)
                          for i in range(bits.bit_length())]
    # Block saturation still cannot corrupt the level bits.
    code = encode_precond_fallback(jnp.int32(FALLBACK_BLOCK_RADIX + 7),
                                   jnp.int32(0b110))
    assert decode_precond_fallback(int(code)) == {
        "block": FALLBACK_BLOCK_RADIX - 1, "coarse": 0b110}
    assert decode_precond_fallback_levels(int(code)) == [False, True, True]


def test_report_decoder_sums_per_level_totals():
    from megba_tpu.observability.report import _decode_fallback_totals

    class FakeTrace:
        precond_fallback = np.asarray([
            int(encode_precond_fallback(jnp.int32(2), jnp.int32(0b10))),
            int(encode_precond_fallback(jnp.int32(0), jnp.int32(0b11))),
            int(encode_precond_fallback(jnp.int32(1), jnp.int32(0))),
            int(encode_precond_fallback(jnp.int32(0), jnp.int32(0b10))),
        ])

    out = _decode_fallback_totals(FakeTrace(), 4)
    assert out == {"block": 3, "coarse": 3, "coarse_levels": [1, 3]}
    # Historical two-level traces: 0/1 high half, no levels list when
    # healthy.
    class Healthy:
        precond_fallback = np.asarray([0, 5, 0])

    assert _decode_fallback_totals(Healthy(), 3) == {
        "block": 5, "coarse": 0}


# ------------------------------------------------- plans + option knobs


def test_multilevel_plan_shrinks_and_partitions():
    s = make_synthetic_bal(num_cameras=40, num_points=300, obs_per_point=4,
                           seed=0, locality="grid")
    mp = build_multilevel_plan(s.cam_idx, s.pt_idx, 40, 300,
                               coarsen_factor=2.0, max_levels=5)
    sizes = mp.level_sizes
    assert all(sizes[i + 1] < sizes[i] for i in range(len(sizes) - 1))
    assert len(sizes) == len(mp.assign) + 1
    # Each assignment is a surjective partition of the previous level.
    for i, a in enumerate(mp.assign):
        assert a.shape == (sizes[i],)
        assert set(np.unique(a)) == set(range(sizes[i + 1]))
    # Composition maps every camera to a top-level cluster.
    top = mp.base.cluster.copy()
    for a in mp.assign:
        top = a[top]
    assert top.shape == (40,) and top.max() < sizes[-1]


def test_plan_cache_keys_on_every_aggregation_knob():
    s = make_synthetic_bal(num_cameras=12, num_points=60, obs_per_point=3,
                           seed=9, locality="ring")
    kw = dict(coarsen_factor=2.0, max_levels=3, smooth_omega=0.0)
    (_, d1), h1 = cached_multilevel_plan(s.cam_idx, s.pt_idx, 12, 60, **kw)
    (_, d2), h2 = cached_multilevel_plan(s.cam_idx.copy(),
                                         s.pt_idx.copy(), 12, 60, **kw)
    assert not h1 and h2
    # Every knob flip is a different fingerprint — a stale hierarchy
    # can never be served for a different SolverOption.
    for flip in (dict(kw, coarsen_factor=3.0), dict(kw, max_levels=4),
                 dict(kw, smooth_omega=0.5)):
        (_, _), hit = cached_multilevel_plan(s.cam_idx, s.pt_idx, 12, 60,
                                             **flip)
        assert not hit, flip
    # Same for the two-level plan's new omega key component.
    (_, _), c1 = cached_cluster_plan(s.cam_idx, s.pt_idx, 12, 60)
    (_, _), c2 = cached_cluster_plan(s.cam_idx, s.pt_idx, 12, 60,
                                     smooth_omega=0.7)
    assert not c1 and not c2


def test_validate_options_rejects_bad_hierarchy_knobs():
    def opt(**skw):
        return ProblemOption(solver_option=SolverOption(**skw))

    with pytest.raises(ValueError, match="coarsen_factor"):
        validate_options(opt(precond=PrecondKind.MULTILEVEL,
                             coarsen_factor=1.0))
    with pytest.raises(ValueError, match="max_levels"):
        validate_options(opt(precond=PrecondKind.MULTILEVEL, max_levels=1))
    with pytest.raises(ValueError, match="max_levels"):
        validate_options(opt(precond=PrecondKind.MULTILEVEL, max_levels=16))
    with pytest.raises(ValueError, match="smooth_omega"):
        validate_options(opt(precond=PrecondKind.TWO_LEVEL,
                             smooth_omega=2.0))
    with pytest.raises(ValueError, match="smooth_omega"):
        validate_options(opt(precond=PrecondKind.JACOBI, smooth_omega=0.5))
    with pytest.raises(ValueError, match="use_schur"):
        validate_options(dataclasses.replace(
            opt(precond=PrecondKind.MULTILEVEL), use_schur=False))
    validate_options(opt(precond=PrecondKind.MULTILEVEL,
                         coarsen_factor=2.0, max_levels=4,
                         smooth_omega=0.6))  # clean


def test_multilevel_requires_plan_operand():
    s, system, Jc, Jp, ci, pi = _system()
    from megba_tpu.solver.pcg import schur_pcg_solve

    with pytest.raises(ValueError, match="cluster plan"):
        schur_pcg_solve(system, Jc, Jp, ci, pi, jnp.asarray(10.0),
                        precond=PrecondKind.MULTILEVEL)


# ----------------------------------------------------- LM-level parity


def _solve(s, kind, world_size=1, max_iter=12, **skw):
    option = ProblemOption(
        world_size=world_size,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-9,
                               epsilon2=1e-12),
        solver_option=SolverOption(max_iter=200, tol=1e-10,
                                   tol_relative=True, refuse_ratio=1e30,
                                   precond=kind, **skw))
    return flat_solve(make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL),
                      s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                      option)


def test_multilevel_reaches_jacobi_optimum_with_fewer_pcg_iters():
    s = make_synthetic_bal(num_cameras=16, num_points=120, obs_per_point=4,
                           seed=0, param_noise=5e-2, pixel_noise=0.3,
                           locality="ring")
    jac = _solve(s, PrecondKind.JACOBI)
    multi = _solve(s, PrecondKind.MULTILEVEL, coarsen_factor=2.0,
                   max_levels=4)
    np.testing.assert_allclose(float(multi.cost), float(jac.cost),
                               rtol=1e-6)
    assert int(multi.pcg_iterations) < int(jac.pcg_iterations)
    # Healthy hierarchy end to end: no per-level degrade in the trace.
    codes = np.asarray(multi.trace.precond_fallback)[
        :int(multi.iterations)]
    assert all(not any(decode_precond_fallback_levels(int(c)))
               for c in codes)


@pytest.mark.slow  # fresh SPMD LM compile — cache-cold this is minutes;
# the full suite (scripts/run_tests.sh) runs it, tier-1 skips
def test_multilevel_world2_iteration_count_parity():
    s = make_synthetic_bal(num_cameras=16, num_points=120, obs_per_point=4,
                           seed=3, param_noise=5e-2, pixel_noise=0.3,
                           locality="ring")
    one = _solve(s, PrecondKind.MULTILEVEL, world_size=1, max_iter=6,
                 coarsen_factor=2.0, max_levels=4)
    two = _solve(s, PrecondKind.MULTILEVEL, world_size=2, max_iter=6,
                 coarsen_factor=2.0, max_levels=4)
    np.testing.assert_allclose(float(two.cost), float(one.cost), rtol=1e-6)
    # Bitwise-equal iteration counts: the sharded hierarchy does the
    # same arithmetic (V/G psum'd once, everything above replicated).
    assert int(two.pcg_iterations) == int(one.pcg_iterations)
    assert int(two.iterations) == int(one.iterations)
