"""Factor registry (megba_tpu/factors/): semantics, parity, servability.

Three layers of coverage:

- REGISTRY SEMANTICS (tier-1, compile-free): duplicate-name refusal,
  typed unknown-factor errors at every dispatch boundary (`flat_solve`,
  `solve_pgo`, `solve_many`, `FleetQueue.submit`), family/dim/robust
  validation, the generalized call-shape-normalising engine cache, and
  factor-dispatched triage/ingestion behaviour.
- NUMERICAL PARITY (slow): every Schur family's engine against dense
  jax autodiff at f64 (~1e-9), the pose families' residual conventions,
  and the BITWISE-identity pin that the registry-dispatched BAL path
  lowers byte-for-byte the program the direct-engine path always built.
- SERVABILITY (slow): each new family solves end-to-end through
  `flat_solve`/`solve_pgo`, and a MIXED-factor fleet through
  `solve_many` + `FleetQueue` with correct (factor, shape-class)
  separation, zero cross-factor retraces (sentinel-certified), and
  batch-mates bitwise against per-factor controls — the acceptance demo
  of ISSUE 13.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megba_tpu.common import (
    AlgoOption,
    JacobianMode,
    ProblemOption,
    SolverOption,
    SolveStatus,
)
from megba_tpu import factors
from megba_tpu.factors import (
    DuplicateFactorError,
    FactorError,
    FactorSpec,
    PoseFactorSpec,
    UnknownFactorError,
    engine_for,
    get_factor,
    list_factors,
    register_factor,
    unregister_factor,
)
from megba_tpu.factors.priors import make_synthetic_priors
from megba_tpu.factors.radial import make_synthetic_radial
from megba_tpu.factors.rig import make_synthetic_rig
from megba_tpu.factors.sim3 import (
    make_synthetic_sim3_graph,
    relative_sim3,
    sim3_between_residual,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.models.planar import make_synthetic_planar
from megba_tpu.solve import flat_solve


def _opt(**kw):
    base = dict(dtype=np.float64,
                algo_option=AlgoOption(max_iter=8),
                solver_option=SolverOption(max_iter=30, tol=1e-9))
    base.update(kw)
    return ProblemOption(**base)


def _factor_problem(name, seed=0):
    """(scene, FleetProblem-ready arrays) for one Schur family."""
    if name == "rig":
        s = make_synthetic_rig(seed=seed)
    elif name == "pinhole_radial":
        s = make_synthetic_radial(seed=seed)
    elif name == "pose_prior":
        s = make_synthetic_priors(seed=seed)
    elif name == "bal":
        s = make_synthetic_bal(seed=seed)
    elif name == "planar":
        s = make_synthetic_planar(seed=seed)
    else:
        raise AssertionError(name)
    return s


# ---------------------------------------------------------------------------
# Registry semantics (tier-1, compile-free)
# ---------------------------------------------------------------------------

def test_builtin_families_registered():
    reg = list_factors()
    for name in ("bal", "planar", "rig", "pinhole_radial", "pose_prior"):
        assert reg[name].kind == "schur", name
    for name in ("se3_between", "sim3_between"):
        assert reg[name].kind == "pose_graph", name


def test_duplicate_registration_refused():
    spec = dataclasses.replace(get_factor("bal"), description="clone")
    with pytest.raises(DuplicateFactorError, match="already registered"):
        register_factor(spec)
    # allow_override is the explicit escape hatch; restore afterwards.
    original = get_factor("bal")
    try:
        register_factor(spec, allow_override=True)
        assert get_factor("bal").description == "clone"
    finally:
        register_factor(original, allow_override=True)


def test_unregister_then_unknown():
    probe = FactorSpec(name="_probe", cam_dim=2, pt_dim=2, obs_dim=1,
                       residual_dim=1, residual_fn=lambda c, p, o: o)
    register_factor(probe)
    assert get_factor("_probe") is probe
    unregister_factor("_probe")
    with pytest.raises(UnknownFactorError, match="_probe"):
        get_factor("_probe")


def test_unknown_factor_names_known_ones():
    with pytest.raises(UnknownFactorError) as ei:
        get_factor("pinhole_radail")  # typo
    assert "pinhole_radial" in str(ei.value)


def test_spec_dim_validation():
    with pytest.raises(FactorError, match="cam_dim"):
        FactorSpec(name="bad", cam_dim=0, pt_dim=3, obs_dim=2,
                   residual_dim=2, residual_fn=lambda c, p, o: o)
    with pytest.raises(FactorError, match="pose_dim"):
        PoseFactorSpec(name="bad", pose_dim=0, meas_dim=6,
                       residual_dim=6, residual_fn=lambda i, j, m: m)


def test_flat_solve_typed_errors_before_any_device_work():
    s = make_synthetic_rig()
    opt = _opt()
    with pytest.raises(UnknownFactorError):
        flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, opt, factor="nope")
    with pytest.raises(FactorError, match="pose-graph family"):
        flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, opt, factor="se3_between")
    # dim mismatch names the axis and the factor
    with pytest.raises(FactorError, match="cameras width 7"):
        flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, opt, factor="bal")
    with pytest.raises(ValueError, match="residual_jac_fn or a registered"):
        flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, opt)


def test_flat_solve_refuses_robust_kernel_on_ineligible_factor():
    from megba_tpu.ops.robust import RobustKind

    s = make_synthetic_priors()
    opt = dataclasses.replace(_opt(), robust_kind=RobustKind.HUBER)
    with pytest.raises(FactorError, match="not robust-kernel eligible"):
        flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, opt, factor="pose_prior")


def test_solve_pgo_typed_errors():
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    g = make_synthetic_pose_graph(num_poses=6, loop_closures=1)
    with pytest.raises(UnknownFactorError):
        solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, _opt(),
                  factor="nope")
    with pytest.raises(FactorError, match="Schur"):
        solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, _opt(),
                  factor="bal")
    with pytest.raises(ValueError, match="pose_dim 7"):
        solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, _opt(),
                  factor="sim3_between")


def test_serving_typed_errors_at_ingestion():
    from megba_tpu.serving.batcher import FleetProblem, _validate_problem

    s = make_synthetic_bal()
    with pytest.raises(UnknownFactorError):
        _validate_problem(FleetProblem.from_synthetic(s, factor="nope"))
    with pytest.raises(FactorError, match="pose-graph"):
        _validate_problem(
            FleetProblem.from_synthetic(s, factor="se3_between"))
    rig = make_synthetic_rig()
    with pytest.raises(FactorError, match="width"):
        _validate_problem(
            FleetProblem.from_synthetic(rig, name="p0", factor="bal"))


def test_queue_submit_typed_unknown_factor():
    from megba_tpu.serving.batcher import FleetProblem
    from megba_tpu.serving.queue import FleetQueue

    s = make_synthetic_bal()
    with FleetQueue(_opt()) as q:
        with pytest.raises(UnknownFactorError):
            q.submit(FleetProblem.from_synthetic(s, factor="nope"))


def test_serving_refuses_robust_kernel_on_ineligible_factor():
    """The fleet boundary makes the SAME robust_ok refusal flat_solve
    makes — a marginalization prior can't be silently IRLS-downweighted
    through solve_many or the queue."""
    from megba_tpu.ops.robust import RobustKind
    from megba_tpu.serving.batcher import FleetProblem, solve_many
    from megba_tpu.serving.queue import FleetQueue

    s = make_synthetic_priors()
    p = FleetProblem(cameras=s.cameras0, points=s.points0, obs=s.obs,
                     cam_idx=s.cam_idx, pt_idx=s.pt_idx,
                     factor="pose_prior")
    opt = dataclasses.replace(_opt(), robust_kind=RobustKind.HUBER)
    with pytest.raises(FactorError, match="not robust-kernel eligible"):
        solve_many([p], opt)
    with FleetQueue(opt) as q:
        with pytest.raises(FactorError, match="not robust-kernel"):
            q.submit(p)


def test_manifest_entries_record_factor_and_warm_per_family():
    """A mixed-factor service's manifest names each bucket's family,
    and warm() resolves each entry's OWN engine — warming a rig bucket
    with the BAL engine would trace-crash on the 7-wide camera blocks
    (the federation cold-start path)."""
    from megba_tpu.serving import compile_pool as cp
    from megba_tpu.serving.shape_class import ShapeClass

    opt = _opt()
    pool = cp.CompilePool()
    shape_rig = ShapeClass(n_cam=4, n_pt=32, n_edge=2048, dtype="float64")
    shape_bal = ShapeClass(n_cam=4, n_pt=32, n_edge=1024, dtype="float64")
    pool.program(engine_for("rig"), opt, shape_rig, 4, 7, 3, 8,
                 factor="rig")
    pool.program(engine_for("bal"), opt, shape_bal, 4, 9, 3, 2,
                 factor="bal")
    entries = {e.get("factor"): e for e in pool.entries()}
    assert entries["rig"]["cd"] == 7 and entries["bal"]["cd"] == 9

    # per-entry engine resolution: factor entries get their family's
    # engine, factor-less (legacy) entries keep the caller's
    sentinel = object()
    assert cp.CompilePool._entry_engine(
        entries["rig"], sentinel, opt) is engine_for("rig")
    assert cp.CompilePool._entry_engine(
        {"shape": {}}, sentinel, opt) is sentinel

    # warm() routes each entry through its own engine (lower_bucket
    # stubbed: this is an engine-ROUTING test, not a compile test)
    seen = []

    class _Stub:
        def compile(self):
            return object()

    real = cp.lower_bucket
    cp.reset_process_cache()
    try:
        cp.lower_bucket = lambda engine, *a, **kw: (
            seen.append(engine), _Stub())[1]
        built = pool.warm(engine_for("bal"), opt,
                          list(entries.values()))
    finally:
        cp.lower_bucket = real
        cp.reset_process_cache()
    assert built == 2
    assert engine_for("rig") in seen and engine_for("bal") in seen


def test_rig_duplicate_pairs_pass_ingestion_bal_refuses():
    """unique_edges drives the duplicate-edge gate per factor."""
    from megba_tpu.io.bal import validate_problem
    from megba_tpu.serving.batcher import FleetProblem, _validate_problem

    s = make_synthetic_rig(rig_cameras=2)
    # The rig fans every (body, point) pair over 2 cameras: repeated
    # index pairs by construction.
    key = s.cam_idx.astype(np.int64) * s.points0.shape[0] + s.pt_idx
    assert np.unique(key).shape[0] < key.shape[0]
    _validate_problem(FleetProblem.from_synthetic(s, factor="rig"))
    with pytest.raises(ValueError, match="duplicate"):
        validate_problem(s.cameras0, s.points0, s.obs, s.cam_idx,
                         s.pt_idx, where="test", unique_edges=True)
    # and the exact same arrays pass with the gate lifted
    validate_problem(s.cameras0, s.points0, s.obs, s.cam_idx,
                     s.pt_idx, where="test", unique_edges=False)


# ---------------------------------------------------------------------------
# Engine cache normalisation (tier-1, compile-free)
# ---------------------------------------------------------------------------

def test_engine_identity_registry_vs_direct():
    """get_factor('bal') resolves to the IDENTICAL engine object the
    historical default call returns — in every mode spelling."""
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    assert engine_for("bal") is make_residual_jacobian_fn()
    assert engine_for("bal", JacobianMode.AUTODIFF) is \
        make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    assert engine_for("bal", JacobianMode.ANALYTICAL) is \
        make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    # memoised: repeat lookups return the same object
    assert engine_for("rig") is engine_for("rig")
    assert engine_for("rig") is not engine_for("pinhole_radial")


def test_engine_for_analytical_refused_without_closed_form():
    with pytest.raises(FactorError, match="no analytical Jacobian"):
        engine_for("rig", JacobianMode.ANALYTICAL)


def test_engine_for_rejects_pose_graph_factor():
    with pytest.raises(FactorError, match="pose-graph"):
        engine_for("sim3_between")


def test_normalized_lru_cache_collapses_spellings():
    from megba_tpu.utils.memo import normalized_lru_cache

    calls = []

    @normalized_lru_cache(maxsize=8)
    def make(a, b=2, c=3):
        calls.append((a, b, c))
        return object()

    r = make(1)
    assert make(1, 2) is r
    assert make(a=1) is r
    assert make(1, c=3, b=2) is r
    assert make(b=2, a=1) is r
    assert len(calls) == 1
    assert make(1, b=9) is not r
    assert len(calls) == 2
    make.cache_clear()
    assert make(1) is not r
    assert len(calls) == 3


def test_normalized_lru_cache_rejects_var_signatures():
    from megba_tpu.utils.memo import normalized_lru_cache

    with pytest.raises(TypeError, match="args"):
        @normalized_lru_cache()
        def bad(*args):
            return None

    with pytest.raises(TypeError, match="kw"):
        @normalized_lru_cache()
        def bad2(**kw):
            return None


def test_batched_solve_program_spellings_one_entry():
    """The serving program factory rides the same normalisation (the
    PR 6 footgun, generalized)."""
    from megba_tpu.serving.compile_pool import batched_solve_program

    engine = engine_for("bal")
    opt = _opt()
    a = batched_solve_program(engine, opt)
    assert batched_solve_program(engine, opt, False) is a
    assert batched_solve_program(engine, opt, faulted=False) is a
    assert batched_solve_program(engine, opt, 0) is a
    assert batched_solve_program(engine, opt, faulted=True) is not a


# ---------------------------------------------------------------------------
# Factor-dispatched triage (tier-1, host NumPy only)
# ---------------------------------------------------------------------------

def test_triage_rig_duplicates_not_flagged():
    from megba_tpu.robustness.triage import (
        CheckKind,
        TriagePolicy,
        check_problem,
    )

    s = make_synthetic_rig(rig_cameras=2)
    report, _ = check_problem(s.cameras0, s.points0, s.obs, s.cam_idx,
                              s.pt_idx, factor=get_factor("rig"))
    assert report.finding(CheckKind.DUPLICATE_EDGE) is None
    # the SAME index structure under default (unique-edge) semantics IS
    # duplicate poison (structural-only policy: the 7-wide rig camera
    # blocks are not BAL-projectable)
    report2, _ = check_problem(s.cameras0, s.points0, s.obs, s.cam_idx,
                               s.pt_idx,
                               policy=TriagePolicy(geometric=False))
    assert report2.finding(CheckKind.DUPLICATE_EDGE) is not None


def test_triage_rig_cheirality_through_hook():
    from megba_tpu.robustness.triage import CheckKind, check_problem

    s = make_synthetic_rig()
    pts = s.points0.copy()
    pts[int(s.pt_idx[0])] = [0.0, 0.0, 6.0]  # behind the rig (z ~ +1)
    report, _ = check_problem(s.cameras0, pts, s.obs, s.cam_idx,
                              s.pt_idx, factor=get_factor("rig"))
    f = report.finding(CheckKind.BEHIND_CAMERA)
    assert f is not None and f.count >= 1
    assert report.geometric


def test_triage_hookless_factor_skips_geometric_pass():
    from megba_tpu.robustness.triage import (
        CheckKind,
        TriageAction,
        TriagePolicy,
        triage_problem,
    )

    s = make_synthetic_priors()
    out = triage_problem(
        s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
        TriagePolicy(on_degenerate=TriageAction.REJECT, geometric=True),
        factor=get_factor("pose_prior"))
    # no projective findings possible, and the report must record that
    # the geometric pass never ran (not "ran clean")
    assert out.report.geometric is False
    for kind in (CheckKind.BEHIND_CAMERA, CheckKind.LOW_PARALLAX,
                 CheckKind.EXTREME_RESIDUAL):
        assert out.report.finding(kind) is None


def test_triage_default_factor_unchanged():
    """factor=None keeps the historical BAL behaviour bit-for-bit."""
    from megba_tpu.robustness.triage import check_problem

    s = make_synthetic_bal(n_behind_camera=2, num_cameras=6,
                           num_points=40)
    r_none, i_none = check_problem(s.cameras0, s.points0, s.obs,
                                   s.cam_idx, s.pt_idx)
    r_bal, i_bal = check_problem(s.cameras0, s.points0, s.obs,
                                 s.cam_idx, s.pt_idx,
                                 factor=get_factor("bal"))
    assert r_none.counts() == r_bal.counts()
    assert np.array_equal(i_none["bad_edge"], i_bal["bad_edge"])
    assert np.array_equal(i_none["weight"], i_bal["weight"])


# ---------------------------------------------------------------------------
# Host-side sim(3) chart maps (tier-1)
# ---------------------------------------------------------------------------

def test_sim3_compose_relative_inverse():
    from megba_tpu.factors.sim3 import compose_sim3

    rng = np.random.default_rng(3)
    a = rng.normal(scale=0.4, size=(32, 7))
    b = rng.normal(scale=0.4, size=(32, 7))
    rel = relative_sim3(a, b)
    assert np.allclose(compose_sim3(a, rel), b, atol=1e-12)


# ---------------------------------------------------------------------------
# Numerical parity + bitwise pins (slow: these compile)
# ---------------------------------------------------------------------------

SCHUR_FAMILIES = ["bal", "planar", "rig", "pinhole_radial", "pose_prior"]


@pytest.mark.slow
@pytest.mark.parametrize("name", SCHUR_FAMILIES)
def test_engine_parity_vs_dense_autodiff_f64(name):
    """Every family's engine (reverse-mode, the production default)
    against jax.jacobian of the spec's own residual at f64 — and the
    forward-mode engine against the same reference."""
    spec = get_factor(name)
    s = _factor_problem(name)
    k = min(16, s.cam_idx.shape[0])
    cams = np.asarray(s.cameras0, np.float64)[s.cam_idx[:k]]
    pts = np.asarray(s.points0, np.float64)[s.pt_idx[:k]]
    obs = np.asarray(s.obs, np.float64)[:k]

    modes = [JacobianMode.AUTODIFF, JacobianMode.AUTODIFF_FORWARD]
    if spec.analytical_fn is not None:
        modes.append(JacobianMode.ANALYTICAL)
    for mode in modes:
        engine = engine_for(spec, mode)
        r, Jc, Jp = engine(cams.T, pts.T, obs.T)
        r = np.asarray(r).T
        Jc = np.asarray(Jc).reshape(spec.residual_dim, spec.cam_dim, k)
        Jp = np.asarray(Jp).reshape(spec.residual_dim, spec.pt_dim, k)
        for e in range(k):
            r_ref = np.asarray(spec.residual_fn(cams[e], pts[e], obs[e]))
            Jc_ref = np.asarray(jax.jacobian(spec.residual_fn, argnums=0)(
                cams[e], pts[e], obs[e]))
            Jp_ref = np.asarray(jax.jacobian(spec.residual_fn, argnums=1)(
                cams[e], pts[e], obs[e]))
            scale = max(1.0, np.abs(Jc_ref).max(), np.abs(Jp_ref).max())
            assert np.allclose(r[e], r_ref, atol=1e-9), (name, mode)
            assert np.allclose(Jc[:, :, e], Jc_ref,
                               atol=1e-9 * scale), (name, mode)
            assert np.allclose(Jp[:, :, e], Jp_ref,
                               atol=1e-9 * scale), (name, mode)


@pytest.mark.slow
def test_sim3_residual_parity_and_se3_reduction():
    """sim(3) Jacobian fwd==rev at f64, zero residual on exact
    measurements, and exact reduction to the SE(3) between residual at
    unit scale."""
    from megba_tpu.models.pgo import between_residual

    g = make_synthetic_sim3_graph(num_poses=12, loop_closures=3)
    pi = jnp.asarray(g.poses_gt[g.edge_i])
    pj = jnp.asarray(g.poses_gt[g.edge_j])
    m = jnp.asarray(g.meas)
    r = jax.vmap(sim3_between_residual)(pi, pj, m)
    assert np.abs(np.asarray(r)).max() < 1e-12

    def stack(f):
        return jax.vmap(f)(pi, pj, m)

    Jf = np.asarray(stack(jax.jacfwd(sim3_between_residual, argnums=0)))
    Jr = np.asarray(stack(jax.jacrev(sim3_between_residual, argnums=0)))
    assert np.allclose(Jf, Jr, atol=1e-9)

    # unit scale: rows 0:6 reduce to the SE(3) between residual
    rng = np.random.default_rng(7)
    a = np.concatenate([rng.normal(scale=0.3, size=(8, 6)),
                        np.zeros((8, 1))], axis=1)
    b = np.concatenate([rng.normal(scale=0.3, size=(8, 6)),
                        np.zeros((8, 1))], axis=1)
    meas = relative_sim3(a, b) * 0.9  # perturbed so r != 0
    meas[:, 6] = 0.0
    r7 = np.asarray(jax.vmap(sim3_between_residual)(
        jnp.asarray(a), jnp.asarray(b), jnp.asarray(meas)))
    r6 = np.asarray(jax.vmap(between_residual)(
        jnp.asarray(a[:, :6]), jnp.asarray(b[:, :6]),
        jnp.asarray(meas[:, :6])))
    assert np.allclose(r7[:, :6], r6, atol=1e-12)
    assert np.abs(r7[:, 6]).max() < 1e-12


@pytest.mark.slow
def test_bal_factor_path_lowers_byte_identical_program():
    """The registry-dispatched BAL solve and the historical direct-
    engine call lower the EXACT same program, byte for byte — the
    refactor's no-regression pin."""
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    s = make_synthetic_bal(num_cameras=4, num_points=24, seed=0)
    opt = _opt(algo_option=AlgoOption(max_iter=3),
               solver_option=SolverOption(max_iter=8, tol=1e-9))
    args = (s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, opt)
    direct = flat_solve(make_residual_jacobian_fn(), *args,
                        use_tiled=False, lower_only=True)
    via_registry = flat_solve(None, *args, use_tiled=False,
                              factor="bal", lower_only=True)
    assert direct.as_text() == via_registry.as_text()


@pytest.mark.slow
def test_pgo_default_factor_is_cached_program_identity():
    """solve_pgo's default and an explicit se3_between spec hit the
    SAME lru-cached program object — no duplicate trace, no drift."""
    from megba_tpu.factors.pose_graph import SPEC
    from megba_tpu.models.pgo import _pgo_program

    opt = _opt()
    a = _pgo_program(opt, 1, 16, np.dtype(np.float64), (), False, SPEC)
    b = _pgo_program(opt, 1, 16, np.dtype(np.float64), (), False, SPEC)
    assert a is b


# ---------------------------------------------------------------------------
# End-to-end solves (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_rig_solves_and_recovers_scene():
    s = make_synthetic_rig(pixel_noise=0.0, param_noise=2e-2)
    r = flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, _opt(), factor="rig")
    assert float(r.cost) < 1e-2 * float(r.initial_cost)
    assert int(r.status) in (SolveStatus.CONVERGED, SolveStatus.MAX_ITER)


@pytest.mark.slow
def test_radial_solves_with_live_distortion_dofs():
    s = make_synthetic_radial(pixel_noise=0.0, param_noise=1e-2)
    r = flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, _opt(), factor="pinhole_radial")
    assert float(r.cost) < 1e-2 * float(r.initial_cost)

    # k1/k2 are OPTIMISABLE state, not constants: start everything
    # else at ground truth, poison only the distortion, and the solve
    # must repair it (on the full perturbed scene above, the 12-dof
    # intrinsics admit compensating directions — cx/cy/k1 trade against
    # rotation on a narrow FOV — so parameter recovery is only
    # identifiable from this targeted start).
    cams = s.cameras_gt.copy()
    cams[:, 10] += 0.05  # ~1000x the generator's k1 spread
    r2 = flat_solve(None, cams, s.points_gt, s.obs, s.cam_idx,
                    s.pt_idx,
                    _opt(algo_option=AlgoOption(max_iter=15,
                                                epsilon1=1e-8)),
                    factor="pinhole_radial")
    k1_err0 = np.abs(cams[:, 10] - s.cameras_gt[:, 10]).max()
    k1_err = np.abs(
        np.asarray(r2.cameras)[:, 10] - s.cameras_gt[:, 10]).max()
    assert float(r2.cost) < 1e-4 * float(r2.initial_cost)
    assert k1_err < 0.1 * k1_err0


@pytest.mark.slow
def test_pose_prior_solve_recovers_exact_priors():
    """With exact priors the optimum IS the prior set (closed form)."""
    s = make_synthetic_priors(prior_noise=0.0, param_noise=5e-2)
    opt = _opt(algo_option=AlgoOption(max_iter=15, epsilon1=1e-9))
    r = flat_solve(None, s.cameras0, s.points0, s.obs, s.cam_idx,
                   s.pt_idx, opt, factor="pose_prior")
    assert np.abs(np.asarray(r.cameras) - s.poses_gt).max() < 1e-5
    # the dummy point never moved
    assert np.array_equal(np.asarray(r.points), s.points0)


@pytest.mark.slow
def test_sim3_pgo_corrects_scale_drift():
    """Noise-free sim(3) loop closing solves to the exact graph — with
    the DEFAULT refuse_ratio.

    The reference's rho-monotonicity refuse (refuse_ratio=1.0,
    schur_pcg_solver.cu:288-296) fires on the sim(3) system's very
    first PCG iteration — the mixed rotation/translation/log-scale
    block makes the preconditioned residual energy non-monotone even
    though CG is converging in A-norm — silently returning dx=0 and
    stalling LM at a 10x cost drop.  ISSUE 15 wired the PR 13 finding
    as a PER-FACTOR DEFAULT (PoseFactorSpec.refuse_ratio=16 on the
    sim3 spec, registry.resolve_refuse_ratio): this test deliberately
    does NOT set refuse_ratio, regression-testing that a caller who
    has never heard of the stall gets the working configuration.
    """
    from megba_tpu.models.pgo import solve_pgo

    g = make_synthetic_sim3_graph(num_poses=24, loop_closures=6,
                                  scale_drift=0.05)
    opt = _opt(algo_option=AlgoOption(max_iter=25, epsilon1=1e-8),
               solver_option=SolverOption(max_iter=80, tol=1e-10,
                                          tol_relative=True))
    r = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, opt,
                  factor="sim3_between")
    assert float(r.cost) < 1e-9 * float(r.initial_cost)
    scale_err0 = np.abs(g.poses0[:, 6] - g.poses_gt[:, 6]).max()
    scale_err = np.abs(
        np.asarray(r.poses)[:, 6] - g.poses_gt[:, 6]).max()
    assert scale_err0 > 0.05  # the drift was real
    assert scale_err < 1e-3  # and it is gone


# ---------------------------------------------------------------------------
# Mixed-factor fleet servability (slow) — the ISSUE 13 acceptance demo
# ---------------------------------------------------------------------------

def _mixed_fleet(n_each=2):
    from megba_tpu.serving.batcher import FleetProblem

    probs = []
    for i in range(n_each):
        probs.append(FleetProblem.from_synthetic(
            make_synthetic_rig(seed=i), name=f"rig{i}", factor="rig"))
        probs.append(FleetProblem.from_synthetic(
            make_synthetic_radial(seed=i), name=f"rad{i}",
            factor="pinhole_radial"))
        s = make_synthetic_priors(seed=i)
        probs.append(FleetProblem(
            cameras=s.cameras0, points=s.points0, obs=s.obs,
            cam_idx=s.cam_idx, pt_idx=s.pt_idx, name=f"pri{i}",
            factor="pose_prior"))
        probs.append(FleetProblem.from_synthetic(
            make_synthetic_bal(seed=i), name=f"bal{i}"))
    return probs


@pytest.mark.slow
def test_mixed_factor_fleet_serves_with_factor_separation(retrace_sentinel):
    """A rig+radial+prior+BAL fleet through solve_many AND FleetQueue:
    every problem terminal, queue bitwise-equal to the synchronous
    path, per-(factor, bucket) batching, and a REPEATED fleet adds
    ZERO traces (the sentinel window fails on any cross-factor or
    repeat retrace)."""
    from megba_tpu.serving.batcher import _group_by_bucket, solve_many
    from megba_tpu.serving.queue import FleetQueue
    from megba_tpu.serving.shape_class import BucketLadder

    opt = _opt(algo_option=AlgoOption(max_iter=6),
               solver_option=SolverOption(max_iter=20, tol=1e-9))
    probs = _mixed_fleet()

    # factor separation at the grouping layer: rig/radial/prior/bal
    # never share a bucket even where shape classes collide
    groups = _group_by_bucket(probs, opt, BucketLadder())
    for (sc, dims, factor), items in groups.items():
        assert {p.factor for _, p in items} == {factor}
    by_factor = {}
    for (sc, dims, factor) in groups:
        by_factor.setdefault(factor, 0)
        by_factor[factor] += 1
    assert set(by_factor) == {"rig", "pinhole_radial", "pose_prior",
                              "bal"}

    control = solve_many(probs, opt)
    assert all(r.status in (SolveStatus.CONVERGED, SolveStatus.MAX_ITER,
                            SolveStatus.RECOVERED) for r in control)

    with FleetQueue(opt, max_batch=4, max_wait_s=0.01) as q:
        futs = [q.submit(p) for p in probs]
        q.flush()
        queued = [f.result() for f in futs]
    for a, b in zip(control, queued):
        assert np.array_equal(a.cameras, b.cameras), a.name
        assert np.array_equal(a.points, b.points), a.name

    # a second identical fleet must be compile-free: everything below
    # this line rides the caches (the sentinel fixture fails the test
    # on ANY duplicate trace in the whole window)
    repeat = solve_many(_mixed_fleet(), opt)
    for a, b in zip(control, repeat):
        assert np.array_equal(a.cameras, b.cameras), a.name


@pytest.mark.slow
def test_mixed_fleet_batchmates_bitwise_vs_per_factor_controls():
    """Each factor's problems solved in the MIXED fleet are bitwise
    identical to the same problems solved in a single-factor fleet:
    batching across factors changes scheduling, never answers."""
    from megba_tpu.serving.batcher import solve_many

    opt = _opt(algo_option=AlgoOption(max_iter=6),
               solver_option=SolverOption(max_iter=20, tol=1e-9))
    mixed = solve_many(_mixed_fleet(), opt)
    by_name = {r.name: r for r in mixed}
    for factor in ("rig", "pinhole_radial", "pose_prior", "bal"):
        sub = [p for p in _mixed_fleet() if p.factor == factor]
        alone = solve_many(sub, opt)
        for p, r in zip(sub, alone):
            m = by_name[p.name]
            assert np.array_equal(m.cameras, r.cameras), p.name
            assert np.array_equal(m.points, r.points), p.name
            assert m.cost == r.cost, p.name


# ---------------------------------------------------------------------------
# Per-factor solver defaults (ISSUE 15 satellite): the PR 13 sim(3)
# refuse stall institutionalised as a spec default
# ---------------------------------------------------------------------------

def test_refuse_ratio_default_resolution():
    from megba_tpu.factors.registry import (
        apply_factor_solver_defaults,
        resolve_refuse_ratio,
    )

    sim3 = get_factor("sim3_between")
    se3 = get_factor("se3_between")
    so = SolverOption()
    # 7-dof family declares its band; the caller's class default yields
    # it without the caller knowing the stall exists.
    assert sim3.refuse_ratio == 16.0
    assert resolve_refuse_ratio(sim3, so) == 16.0
    # An explicit caller setting always wins.
    assert resolve_refuse_ratio(
        sim3, dataclasses.replace(so, refuse_ratio=4.0)) == 4.0
    assert resolve_refuse_ratio(
        sim3, dataclasses.replace(so, refuse_ratio=1e30)) == 1e30
    # Families without a declared default change nothing.
    assert se3.refuse_ratio is None
    assert resolve_refuse_ratio(se3, so) == so.refuse_ratio


def test_apply_factor_solver_defaults_object_identity():
    """No resolution difference -> the SAME option object comes back
    (jit/program caches keyed on the option must not split); a
    resolved default -> a replaced copy carrying it."""
    from megba_tpu.factors.registry import apply_factor_solver_defaults

    opt = _opt()
    assert apply_factor_solver_defaults(get_factor("se3_between"),
                                        opt) is opt
    # sim3 at an explicit refuse: also unchanged (caller wins).
    explicit = _opt(solver_option=SolverOption(refuse_ratio=8.0))
    assert apply_factor_solver_defaults(get_factor("sim3_between"),
                                        explicit) is explicit
    resolved = apply_factor_solver_defaults(get_factor("sim3_between"),
                                            opt)
    assert resolved is not opt
    assert resolved.solver_option.refuse_ratio == 16.0
    # everything else untouched
    assert dataclasses.replace(
        resolved, solver_option=opt.solver_option) == opt


def test_schur_factor_defaults_resolve_in_flat_solve():
    """A Schur-family spec carrying a refuse default gets the same
    treatment at the flat_solve seam: validated by registering a
    throwaway factor and checking the typed validation path still
    resolves (no solve — the wrong-width arrays are refused AFTER the
    spec resolves, proving dispatch reaches the resolver)."""
    from megba_tpu.factors.registry import (
        FactorError,
        register_factor,
        resolve_refuse_ratio,
        unregister_factor,
    )

    spec = FactorSpec(
        name="_test_refuse_default", cam_dim=9, pt_dim=3, obs_dim=2,
        residual_dim=2, residual_fn=lambda c, p, o: o,
        refuse_ratio=32.0)
    register_factor(spec)
    try:
        assert resolve_refuse_ratio(spec, SolverOption()) == 32.0
        with pytest.raises(FactorError, match="width"):
            flat_solve(None, np.zeros((2, 5), np.float32),
                       np.zeros((2, 3), np.float32),
                       np.zeros((4, 2), np.float32),
                       np.zeros(4, np.int32), np.zeros(4, np.int32),
                       _opt(dtype=np.float32),
                       factor="_test_refuse_default")
    finally:
        unregister_factor("_test_refuse_default")
