"""Edge cases the reference's design makes impossible or implicit.

The reference's CSR build requires ONE edge per (camera, point) pair
(race-freedom of makeHpl relies on it, build_linear_system.cu:55-76);
segment_sum has no such constraint — duplicate pairs must simply
accumulate.  Facade misuse must fail loudly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu import (
    BaseEdge,
    BaseProblem,
    CameraVertex,
    PointVertex,
    ProblemOption,
)
from megba_tpu.common import AlgoOption, JacobianMode, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.linear_system import build_schur_system, weight_system_inputs
from megba_tpu.ops.residuals import make_residual_jacobian_fn


def test_duplicate_camera_point_pairs_accumulate():
    # Two identical edges must contribute exactly twice one edge's blocks.
    s = make_synthetic_bal(num_cameras=3, num_points=10, obs_per_point=2, seed=0)
    cams, pts = jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    def build(cam_idx, pt_idx, obs):
        cam_idx, pt_idx, obs = (jnp.asarray(cam_idx), jnp.asarray(pt_idx),
                                jnp.asarray(obs.T))
        r, Jc, Jp = f(cams[:, cam_idx], pts[:, pt_idx], obs)
        r, Jc, Jp = weight_system_inputs(r, Jc, Jp, cam_idx, pt_idx,
                                         jnp.ones(obs.shape[1]))
        return build_schur_system(r, Jc, Jp, cam_idx, pt_idx, 3, 10)

    one = build(s.cam_idx[:1], s.pt_idx[:1], s.obs[:1])
    two = build(np.repeat(s.cam_idx[:1], 2), np.repeat(s.pt_idx[:1], 2),
                np.repeat(s.obs[:1], 2, axis=0))
    c = int(s.cam_idx[0])
    np.testing.assert_allclose(np.asarray(two.Hpp[c]),
                               2 * np.asarray(one.Hpp[c]), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(two.g_cam[:, c]),
                               2 * np.asarray(one.g_cam[:, c]), rtol=1e-12)


def test_facade_rejects_unknown_vertex_edge():
    pb = BaseProblem()
    c = CameraVertex(np.zeros(9))
    p = PointVertex(np.zeros(3))
    pb.append_vertex(0, c)  # p NOT appended
    with pytest.raises(ValueError, match="not in the problem"):
        pb.append_edge(BaseEdge([c, p], measurement=np.zeros(2)))


def test_facade_rejects_missing_measurement():
    pb = BaseProblem()
    c, p = CameraVertex(np.zeros(9)), PointVertex(np.zeros(3))
    pb.append_vertex(0, c)
    pb.append_vertex(1, p)
    with pytest.raises(ValueError, match="measurement"):
        pb.append_edge(BaseEdge([c, p]))


def test_facade_rejects_duplicate_vertex_id():
    pb = BaseProblem()
    pb.append_vertex(7, CameraVertex(np.zeros(9)))
    with pytest.raises(ValueError, match="duplicate"):
        pb.append_vertex(7, PointVertex(np.zeros(3)))


def test_erase_camera_vertex():
    s = make_synthetic_bal(num_cameras=4, num_points=20, obs_per_point=2, seed=3)
    pb = BaseProblem(ProblemOption(
        algo_option=AlgoOption(max_iter=8, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=60, tol=1e-8, tol_relative=True,
                                   refuse_ratio=1e30)))
    cams = [CameraVertex(c) for c in s.cameras0]
    pts = [PointVertex(p) for p in s.points0]
    for i, v in enumerate(cams):
        pb.append_vertex(i, v)
    for j, v in enumerate(pts):
        pb.append_vertex(100 + j, v)
    for c, p, uv in zip(s.cam_idx, s.pt_idx, s.obs):
        pb.append_edge(BaseEdge([cams[c], pts[p]], measurement=uv))
    pb.erase_vertex(2)  # a camera this time
    assert all(e.vertices[0] is not cams[2] for e in pb._edges)
    res = pb.solve()
    assert np.isfinite(float(res.cost))


def test_custom_edge_attribute_not_served_stale_across_problems():
    # Two problems using the same custom edge CLASS but different
    # per-instance constants: each solve must trace ITS OWN prototype
    # (a class-level engine cache once served problem 1's constant to
    # problem 2).
    class ScaledEdge(BaseEdge):
        def __init__(self, *args, scale=1.0, **kw):
            super().__init__(*args, **kw)
            self.scale = scale

        def forward(self):
            cam = self.vertex_estimation(0)
            pt = self.vertex_estimation(1)
            from megba_tpu.ops.residuals import bal_residual
            return self.scale * bal_residual(cam, pt, self.get_measurement())

    s = make_synthetic_bal(num_cameras=3, num_points=12, obs_per_point=2, seed=6)

    def initial_cost(scale):
        pb = BaseProblem(ProblemOption(
            algo_option=AlgoOption(max_iter=1),
            solver_option=SolverOption(max_iter=5)))
        cams = [CameraVertex(c) for c in s.cameras0]
        pts = [PointVertex(p) for p in s.points0]
        for i, v in enumerate(cams):
            pb.append_vertex(i, v)
        for j, v in enumerate(pts):
            pb.append_vertex(100 + j, v)
        for c, p, uv in zip(s.cam_idx, s.pt_idx, s.obs):
            pb.append_edge(ScaledEdge([cams[c], pts[p]], measurement=uv,
                                      scale=scale))
        return float(pb.solve().initial_cost)

    c1 = initial_cost(1.0)
    c10 = initial_cost(10.0)
    np.testing.assert_allclose(c10 / c1, 100.0, rtol=1e-6)


def test_edge_type_resets_when_all_edges_erased():
    s = make_synthetic_bal(num_cameras=2, num_points=4, obs_per_point=1, seed=7)
    pb = BaseProblem()
    cams = [CameraVertex(c) for c in s.cameras0]
    pts = [PointVertex(p) for p in s.points0]
    for i, v in enumerate(cams):
        pb.append_vertex(i, v)
    for j, v in enumerate(pts):
        pb.append_vertex(100 + j, v)

    class EdgeA(BaseEdge):
        pass

    pb.append_edge(EdgeA([cams[0], pts[0]], measurement=np.zeros(2)))
    pb.erase_vertex(100)  # removes the only edge
    assert not pb._edges

    class EdgeB(BaseEdge):
        pass

    # Must be accepted: the problem has zero edges of any type now.
    pb.append_edge(EdgeB([cams[0], pts[1]], measurement=np.zeros(2)))


def test_all_vertices_fixed_is_a_noop_solve():
    s = make_synthetic_bal(num_cameras=3, num_points=12, obs_per_point=2, seed=4)
    pb = BaseProblem(ProblemOption(
        algo_option=AlgoOption(max_iter=5),
        solver_option=SolverOption(max_iter=30)))
    cams = [CameraVertex(c, fixed=True) for c in s.cameras0]
    pts = [PointVertex(p, fixed=True) for p in s.points0]
    for i, v in enumerate(cams):
        pb.append_vertex(i, v)
    for j, v in enumerate(pts):
        pb.append_vertex(100 + j, v)
    for c, p, uv in zip(s.cam_idx, s.pt_idx, s.obs):
        pb.append_edge(BaseEdge([cams[c], pts[p]], measurement=uv))
    res = pb.solve()
    np.testing.assert_allclose(float(res.cost), float(res.initial_cost), rtol=1e-12)
    np.testing.assert_array_equal(cams[0].estimation, s.cameras0[0])
