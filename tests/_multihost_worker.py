"""Worker for the two-process multi-host smoke test (test_multihost.py).

Run as `python tests/_multihost_worker.py <process_id> <port>`.  Each of
the two processes wires jax.distributed over localhost CPU, checks the
idempotency/error contract of `initialize_multihost`, and runs one
cross-process psum over the global 2-device mesh — the same collective
the sharded solve rides (SURVEY.md §2.3's communication backend, here
spanning processes instead of one process's devices).
"""

import sys

import numpy as np

import jax

# The axon TPU plugin's sitecustomize forces jax_platforms to
# "axon,cpu"; pin CPU before any backend init (same move as
# tests/conftest.py) so this worker never touches the tunnel.
jax.config.update("jax_platforms", "cpu")

from megba_tpu.parallel.multihost import (  # noqa: E402
    enable_cpu_cross_process_collectives,
    initialize_multihost,
)


def main() -> None:
    pid, port = int(sys.argv[1]), sys.argv[2]
    addr = f"localhost:{port}"
    # The plain XLA:CPU client refuses multiprocess computations; select
    # gloo TCP collectives BEFORE any backend init.  The orchestrating
    # test is skipped when this jaxlib has no gloo, so a False return
    # here is a hard error.
    assert enable_cpu_cross_process_collectives(), \
        "jaxlib has no gloo CPU collectives"
    info = initialize_multihost(addr, 2, pid)
    assert info["process_count"] == 2, info
    assert info["process_index"] == pid, info
    assert info["global_devices"] >= 2, info

    # Exact-repeat call is idempotent...
    info2 = initialize_multihost(addr, 2, pid)
    assert info2 == info, (info, info2)
    # ...but different explicit parameters must fail loudly (silently
    # ignoring them would leave hosts solo-solving).
    try:
        initialize_multihost(addr, 3, pid)
    except RuntimeError:
        pass
    else:
        raise AssertionError("expected RuntimeError on mismatched params")

    # One cross-process collective over the global mesh: each process
    # contributes its rank+1; the psum must see both.
    import jax.numpy as jnp  # noqa: F401
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = np.asarray(jax.devices()[:2])
    mesh = Mesh(devs, ("edge",))
    sharding = NamedSharding(mesh, P("edge"))
    local = np.full((1,), pid + 1, np.float32)
    x = jax.make_array_from_process_local_data(sharding, local, (2,))
    f = jax.jit(shard_map(
        lambda v: jax.lax.psum(v, "edge"), mesh=mesh,
        in_specs=P("edge"), out_specs=P()))
    out = f(x)
    assert float(np.asarray(out)[0]) == 3.0, np.asarray(out)
    print(f"worker {pid} OK", flush=True)


if __name__ == "__main__":
    main()
