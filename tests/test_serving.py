"""Serving-layer tests: shape buckets, batched solves, queue, pool.

Compile discipline: tier-1 runs at ~80% of its time budget, so every
test here draws from ONE canonical option per dtype (OPT64 / OPT32) and
a small closed set of (bucket, lanes) shapes — the jit caches and the
persistent compile cache make the marginal cost of each extra test a
solve, not a compile.  Every test that traces/compiles a solver program
is additionally marked `slow`: the tier-1 sweep (`pytest -m 'not
slow'`) keeps only the host-side property/unit tests, and the full
two-process lane (scripts/run_tests.sh, no filter) runs everything.
"""

import dataclasses
import json
import time

import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    ProblemOption,
    SolverOption,
    SolveStatus,
)
from megba_tpu.io.synthetic import make_fleet, make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.serving import (
    BucketLadder,
    CompilePool,
    FleetProblem,
    FleetQueue,
    FleetStats,
    classify,
    pad_to_class,
    solve_many,
)
from megba_tpu.solve import flat_solve

TERMINAL = {int(s) for s in SolveStatus}

OPT64 = ProblemOption(dtype=np.float64,
                      algo_option=AlgoOption(max_iter=6),
                      solver_option=SolverOption(max_iter=12, tol=1e-10))
OPT32 = dataclasses.replace(OPT64, dtype=np.float32)


def _mk(seed, n_pt, n_cam=4, dtype=np.float64):
    s = make_synthetic_bal(num_cameras=n_cam, num_points=n_pt,
                           obs_per_point=3, seed=seed, param_noise=2e-2,
                           pixel_noise=0.3, dtype=dtype)
    return FleetProblem.from_synthetic(s, name=f"s{seed}_p{n_pt}")


def _bits(a) -> bytes:
    return np.asarray(a).tobytes()


# ---------------------------------------------------------------------------
# Bucket ladder properties
# ---------------------------------------------------------------------------

def test_ladder_monotone_and_covering():
    ladder = BucketLadder()
    r = np.random.default_rng(0)
    ns = np.concatenate([np.arange(1, 70),
                         r.integers(1, 3_000_000, size=300)])
    for bucket in (ladder.bucket_cams, ladder.bucket_points,
                   ladder.bucket_edges, ladder.bucket_lanes):
        got = [bucket(int(n)) for n in sorted(ns)]
        # covering: a problem always fits its bucket
        assert all(b >= n for b, n in zip(got, sorted(ns)))
        # monotone: more of anything never lands in a smaller bucket
        assert all(b2 >= b1 for b1, b2 in zip(got, got[1:]))
        # idempotent: a bucket is its own bucket (ladder is a closure)
        assert all(bucket(b) == b for b in got)


def test_ladder_is_powers_of_two_over_floor():
    ladder = BucketLadder(cam_floor=4, pt_floor=16)
    for n in range(1, 200):
        b = ladder.bucket_cams(n)
        assert b % 4 == 0 and (b // 4) & (b // 4 - 1) == 0
    # edge buckets stay EDGE_QUANTUM multiples (solver invariant)
    from megba_tpu.core.fm import EDGE_QUANTUM

    for n in (1, 100, 2048, 2049, 5000, 100_000):
        assert ladder.bucket_edges(n) % EDGE_QUANTUM == 0


def test_ladder_validation():
    with pytest.raises(ValueError):
        BucketLadder(cam_floor=0)
    with pytest.raises(ValueError):
        BucketLadder(edge_floor=1000)  # not an EDGE_QUANTUM multiple
    with pytest.raises(ValueError):
        classify(0, 10, 10, np.float64, BucketLadder())


def test_pad_to_class_invariants():
    p = _mk(5, 37, n_cam=5)
    sc = classify(*p.dims(), np.float64, BucketLadder())
    pp = pad_to_class(p.cameras, p.points, p.obs, p.cam_idx, p.pt_idx, sc)
    assert pp.cameras.shape[0] == sc.n_cam
    assert pp.points.shape[0] == sc.n_pt
    assert pp.obs.shape[0] == sc.n_edge
    # padded edges masked out, indices in range, cam stream sorted
    n = pp.n_edge
    assert pp.mask[:n].all() and not pp.mask[n:].any()
    assert pp.cam_idx.max() < pp.n_cam and pp.pt_idx.max() < pp.n_pt
    assert np.all(np.diff(pp.cam_idx) >= 0)
    # pad region flagged fixed, real region not
    assert not pp.cam_fixed[:pp.n_cam].any() and pp.cam_fixed[pp.n_cam:].all()
    assert not pp.pt_fixed[:pp.n_pt].any() and pp.pt_fixed[pp.n_pt:].all()
    # a problem too big for the class is rejected
    small = dataclasses.replace(sc, n_cam=2)
    with pytest.raises(ValueError):
        pad_to_class(p.cameras, p.points, p.obs, p.cam_idx, p.pt_idx, small)


# ---------------------------------------------------------------------------
# Padding exactness + lane invariance (the fleet numerics contract)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_padded_solve_bitwise_equals_unpadded_f64():
    """Bucket padding is an exact no-op: the same problem solved at its
    minimal shape class and at a strictly larger one (more cameras,
    points AND edges) produces bitwise-identical parameters, cost and
    iteration count."""
    p = _mk(3, 32)
    base = solve_many([p], OPT64)[0]
    big = solve_many([p], OPT64, ladder=BucketLadder(
        cam_floor=8, pt_floor=64, edge_floor=4096))[0]
    assert big.shape != base.shape
    assert _bits(base.cameras) == _bits(big.cameras)
    assert _bits(base.points) == _bits(big.points)
    assert _bits(base.cost) == _bits(big.cost)
    assert base.iterations == big.iterations
    assert base.status == big.status


@pytest.mark.slow
def test_padded_solve_edge_axis_bitwise_f32():
    """f32: zero-padding the EDGE axis to a bigger power-of-two bucket
    keeps the whole parameter trajectory bitwise (the compensated-sum
    fold absorbs appended zero rows exactly); the carried cost scalar
    may differ in its last ulps (the [od, nE] ravel interleaves the two
    observation rows), so it gets an ulp-tight allclose instead."""
    p = _mk(3, 32, dtype=np.float32)
    base = solve_many([p], OPT32)[0]
    big = solve_many([p], OPT32,
                     ladder=BucketLadder(edge_floor=4096))[0]
    assert big.shape.n_edge == 2 * base.shape.n_edge
    assert _bits(base.cameras) == _bits(big.cameras)
    assert _bits(base.points) == _bits(big.points)
    assert base.iterations == big.iterations
    assert base.status == big.status
    np.testing.assert_allclose(big.cost, base.cost, rtol=1e-5)


@pytest.mark.slow
def test_padded_solve_campt_equivalence_f32():
    """f32 camera/point padding reorders the compensated reductions
    (interleaved zeros in the feature-major ravel), so exact bitwise is
    out of reach — but the solve must land on the same answer within
    the acceptance band (rtol 1e-6 on cost) and terminate."""
    p = _mk(3, 32, dtype=np.float32)
    base = solve_many([p], OPT32)[0]
    big = solve_many([p], OPT32, ladder=BucketLadder(
        cam_floor=16, pt_floor=64))[0]
    assert big.status in TERMINAL and base.status in TERMINAL
    np.testing.assert_allclose(big.cost, base.cost, rtol=1e-6)
    # Parameters sit in the f32 convergence basin: weakly-constrained
    # directions (the k1/k2 distortion terms) wander ~1e-4 relative at
    # identical cost, so the parameter band is looser than the cost's.
    np.testing.assert_allclose(big.cameras, base.cameras,
                               rtol=1e-3, atol=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("dtype,opt", [(np.float64, OPT64),
                                       (np.float32, OPT32)],
                         ids=["f64", "f32"])
def test_lane_placement_invariance_bitwise(dtype, opt):
    """The fleet isolation contract: at a fixed (bucket, lane count),
    a problem's result is bitwise independent of its lane position and
    of WHO its batch-mates are — and reruns are deterministic."""
    p, q, r = (_mk(3, 32, dtype=dtype), _mk(7, 29, dtype=dtype),
               _mk(11, 31, dtype=dtype))
    a = solve_many([p, q], opt)
    assert a[0].shape == a[1].shape  # same bucket (29/31 pts pad to 32)
    b = solve_many([q, p], opt)  # p moves to lane 1
    c = solve_many([p, r], opt)  # different batch-mate
    d = solve_many([p, q], opt)  # rerun
    for other in (b[1], c[0], d[0]):
        assert _bits(a[0].cameras) == _bits(other.cameras)
        assert _bits(a[0].points) == _bits(other.points)
        assert _bits(a[0].cost) == _bits(other.cost)


# ---------------------------------------------------------------------------
# The acceptance fleet: 16 heterogeneous problems vs flat_solve
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_16_matches_flat_solve_one_compile_per_bucket():
    """solve_many over a 16-problem heterogeneous fleet returns
    per-problem params/cost/SolveStatus matching individual flat_solve
    runs, with the retrace sentinel certifying <= 1 batched-program
    compile per shape bucket (and zero on a rerun)."""
    from megba_tpu.analysis import retrace

    fleet = make_fleet(16, size_range=(12, 96), seed=0)
    probs = [FleetProblem.from_synthetic(s, name=f"fleet{i}")
             for i, s in enumerate(fleet)]
    ladder = BucketLadder()
    stats = FleetStats()

    base = retrace.snapshot()
    results = solve_many(probs, OPT64, ladder=ladder, stats=stats)
    new = {k: v for k, v in retrace.snapshot().items()
           if k[0] == "serving.batched"
           and v > base.get(k, 0)}
    buckets = {(r.shape, r.lanes) for r in results}
    # one compile per (bucket, lane-count), ever — and never a
    # duplicate signature (that would be a jit cache bust)
    assert all(v - base.get(k, 0) <= 1 for k, v in new.items()), new
    assert len(new) <= len(buckets), (new, buckets)

    # a rerun of the same fleet compiles NOTHING new
    base2 = retrace.snapshot()
    again = solve_many(probs, OPT64, ladder=ladder)
    assert not {k: v for k, v in retrace.snapshot().items()
                if k[0] == "serving.batched" and v > base2.get(k, 0)}

    f = make_residual_jacobian_fn()
    for p, res, res2 in zip(probs, results, again):
        # determinism across calls
        assert _bits(res.cameras) == _bits(res2.cameras)
        assert _bits(res.cost) == _bits(res2.cost)
        assert res.status in TERMINAL
        # individual reference run AT the same shape class (flat_solve
        # on the padded arrays + fixed masks + the bucket's edge mask —
        # identical static shapes AND identical masked-edge no-ops, so
        # the only difference is batching itself)
        pp = pad_to_class(p.cameras, p.points, p.obs, p.cam_idx,
                          p.pt_idx, res.shape)
        ref = flat_solve(f, pp.cameras, pp.points, pp.obs, pp.cam_idx,
                         pp.pt_idx, OPT64, edge_mask=pp.mask,
                         cam_fixed=pp.cam_fixed, pt_fixed=pp.pt_fixed,
                         use_tiled=False)
        assert int(ref.status) == res.status, p.name
        np.testing.assert_allclose(res.cost, np.asarray(ref.cost),
                                   rtol=1e-6, err_msg=p.name)
        np.testing.assert_allclose(
            res.cameras, np.asarray(ref.cameras)[:pp.n_cam],
            rtol=1e-6, atol=1e-8, err_msg=p.name)
        np.testing.assert_allclose(
            res.points, np.asarray(ref.points)[:pp.n_pt],
            rtol=1e-6, atol=1e-8, err_msg=p.name)
        # padded camera/point lanes never moved off their zero padding
        assert not np.any(np.asarray(ref.cameras)[pp.n_cam:])

    # stats coherence for the run
    d = stats.as_dict()
    assert d["problems"] == 16
    assert d["batches"] == len(buckets)
    assert 0.0 < d["padding_waste"] < 1.0
    assert d["problems_per_sec"] > 0.0


@pytest.mark.slow
def test_fleet_vs_natural_flat_solve_rtol():
    """Cross-shape check: lanes also match flat_solve at the problem's
    NATURAL (unbucketed) shapes within the acceptance band."""
    probs = [_mk(3, 32), _mk(5, 37, n_cam=5), _mk(9, 20, n_cam=3)]
    results = solve_many(probs, OPT64)
    f = make_residual_jacobian_fn()
    for p, res in zip(probs, results):
        ref = flat_solve(f, p.cameras, p.points, p.obs, p.cam_idx,
                         p.pt_idx, OPT64, use_tiled=False)
        assert int(ref.status) == res.status
        assert int(ref.iterations) == res.iterations
        np.testing.assert_allclose(res.cost, np.asarray(ref.cost),
                                   rtol=1e-6)
        np.testing.assert_allclose(res.cameras, np.asarray(ref.cameras),
                                   rtol=1e-6, atol=1e-8)


def test_make_fleet_deterministic_and_prefix_stable():
    a = make_fleet(8, size_range=(12, 96), seed=0)
    b = make_fleet(8, size_range=(12, 96), seed=0)
    for x, y in zip(a, b):
        assert _bits(x.cameras0) == _bits(y.cameras0)
        assert _bits(x.obs) == _bits(y.obs)
    # growing the fleet never reshuffles existing members
    c = make_fleet(4, size_range=(12, 96), seed=0)
    for x, y in zip(c, a):
        assert _bits(x.obs) == _bits(y.obs)
    # a different seed is a different fleet
    d = make_fleet(4, size_range=(12, 96), seed=1)
    assert any(_bits(x.obs) != _bits(y.obs) for x, y in zip(d, a))
    # heterogeneous sizes
    assert len({s.points_gt.shape[0] for s in a}) > 1
    with pytest.raises(ValueError):
        make_fleet(0)


# ---------------------------------------------------------------------------
# Compile pool + warmup manifests
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_compile_pool_warm_manifest_roundtrip(tmp_path):
    """Warming from a manifest AOT-compiles the bucket; a dispatch that
    follows runs the precompiled executable WITHOUT tracing anything
    new (the sentinel proves first-request latency is dispatch-only)."""
    from megba_tpu.analysis import retrace

    engine = make_residual_jacobian_fn()
    p = _mk(21, 16, n_cam=3)
    ladder = BucketLadder()
    sc = classify(*p.dims(), OPT64.dtype, ladder)

    # A config no other test dispatches, so the warmed program is
    # guaranteed fresh regardless of test ordering.
    opt = dataclasses.replace(OPT64, algo_option=AlgoOption(max_iter=4))
    stats = FleetStats()
    pool = CompilePool(stats=stats)
    entry = {"shape": sc.to_dict(), "lanes": 1, "cd": 9, "pd": 3, "od": 2}
    assert pool.warm(engine, opt, [entry]) == 1
    assert pool.warm(engine, opt, [entry]) == 0  # idempotent

    manifest = tmp_path / "warmup.json"
    pool.save_manifest(str(manifest), option=opt)
    doc = json.loads(manifest.read_text())
    assert doc["schema"].startswith("megba_tpu.fleet_manifest")
    assert doc["entries"] == [entry]

    # a fresh pool warming the same manifest finds everything built
    pool2 = CompilePool()
    assert pool2.warm_from_manifest(str(manifest), engine, opt) == 0

    # dispatch through the warmed pool: zero new traces of any site
    base = retrace.snapshot()
    res = solve_many([p], opt, ladder=ladder, pool=pool, stats=stats)[0]
    new = {k: v for k, v in retrace.snapshot().items()
           if v > base.get(k, 0)}
    assert not new, f"warmed dispatch traced: {new}"
    assert res.status in TERMINAL
    assert stats.pool_hits >= 1

    # a manifest recorded under a different option fingerprint warns
    # (checked against an EMPTY manifest so the test stays compile-free)
    empty = tmp_path / "empty.json"
    CompilePool().save_manifest(str(empty), option=OPT64)
    other = dataclasses.replace(
        OPT64, algo_option=AlgoOption(max_iter=5))
    with pytest.warns(UserWarning, match="different option"):
        assert CompilePool().warm_from_manifest(
            str(empty), engine, other) == 0

    with pytest.raises(ValueError, match="not a fleet warmup manifest"):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"schema": "nope"}))
        CompilePool().warm_from_manifest(str(bad), engine, OPT64)


# ---------------------------------------------------------------------------
# Async dispatch queue
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_queue_max_batch_flush_matches_solve_many():
    """8 same-bucket problems through a max_batch=4 queue flush as two
    4-lane batches whose results are bitwise what solve_many produces
    for the same 4-problem batches (lane invariance at fixed B)."""
    probs = [_mk(100 + i, 29 + (i % 4)) for i in range(8)]  # one bucket
    with FleetQueue(OPT64, max_batch=4, max_wait_s=30.0) as q:
        futures = [q.submit(p) for p in probs]
        got = [f.result(timeout=600) for f in futures]
    assert all(g.lanes == 4 for g in got)
    ref = solve_many(probs[:4], OPT64) + solve_many(probs[4:], OPT64)
    for g, r in zip(got, ref):
        assert _bits(g.cameras) == _bits(r.cameras)
        assert _bits(g.cost) == _bits(r.cost)
        assert g.status in TERMINAL
        assert g.latency_s > 0.0


@pytest.mark.slow
def test_queue_deadline_flush():
    """A lone problem must not wait forever for batch-mates: the
    max_wait deadline flushes it (lanes == 1)."""
    p = _mk(3, 32)
    with FleetQueue(OPT64, max_batch=64, max_wait_s=0.05) as q:
        t0 = time.monotonic()
        fut = q.submit(p)
        res = fut.result(timeout=600)
        assert res.lanes == 1
        assert time.monotonic() - t0 >= 0.05
    assert res.status in TERMINAL


@pytest.mark.slow
def test_queue_flush_and_close_drain():
    p, p2 = _mk(3, 32), _mk(7, 29)
    q = FleetQueue(OPT64, max_batch=64, max_wait_s=600.0)
    try:
        f1 = q.submit(p)
        q.flush()  # ignores the 10-minute deadline
        assert f1.result(timeout=600).status in TERMINAL
        f2 = q.submit(p2)
    finally:
        q.close()  # drains f2
    assert f2.result(timeout=600).status in TERMINAL
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(p)


@pytest.mark.slow
def test_queue_failed_batch_propagates_and_keeps_serving():
    """A batch that dies (here: a malformed problem that cannot trace)
    rejects ITS futures with the real error; the queue keeps serving
    later submissions."""
    bad = _mk(3, 32)
    bad = dataclasses.replace(bad, cameras=bad.cameras[:, :2])  # cd=2
    good = _mk(3, 32)
    with FleetQueue(OPT64, max_batch=1, max_wait_s=10.0) as q:
        fb = q.submit(bad)
        with pytest.raises(Exception):
            fb.result(timeout=600)
        fg = q.submit(good)
        assert fg.result(timeout=600).status in TERMINAL


def test_queue_validation():
    with pytest.raises(ValueError):
        FleetQueue(OPT64, max_batch=0)
    with pytest.raises(ValueError):
        FleetQueue(OPT64, max_wait_s=-1.0)
    with pytest.raises(ValueError, match="world_size"):
        solve_many([_mk(3, 32)],
                   dataclasses.replace(OPT64, world_size=2))


# ---------------------------------------------------------------------------
# Stats + plan cache + telemetry/CLI satellites
# ---------------------------------------------------------------------------

def test_fleet_stats_metrics():
    s = FleetStats()
    s.record_batch("b1", lanes=4, n_real=3, edges_real=300,
                   edge_bucket=2048, wall_s=0.5)
    s.record_batch("b2", lanes=1, n_real=1, edges_real=2048,
                   edge_bucket=2048, wall_s=0.5)
    s.record_pool(True)
    s.record_pool(False)
    d = s.as_dict()
    assert d["problems"] == 4 and d["batches"] == 2
    assert d["problems_per_sec"] == pytest.approx(4.0)
    waste = 1.0 - (300 + 2048) / (5 * 2048)
    assert d["padding_waste"] == pytest.approx(waste)
    assert d["bucket_occupancy"]["b1"] == pytest.approx(0.75)
    assert d["pool_hit_rate"] == pytest.approx(0.5)
    assert "problems/s" in s.report()


def test_plan_cache_capacity_env_and_evictions(monkeypatch):
    """MEGBA_PLAN_CACHE resizes the DualPlans LRU; evictions count."""
    from megba_tpu.ops import segtiles

    def graph(seed):
        r = np.random.default_rng(seed)
        cam = np.sort(r.integers(0, 4, size=32)).astype(np.int32)
        pt = r.integers(0, 16, size=32).astype(np.int32)
        return cam, pt

    monkeypatch.setenv("MEGBA_PLAN_CACHE", "2")
    segtiles._PLAN_CACHE.clear()
    base_ev = segtiles.plan_cache_evictions()
    for seed in range(4):  # 4 distinct graphs through a capacity-2 LRU
        cam, pt = graph(seed)
        _, hit = segtiles.cached_dual_plans(cam, pt, 4, 16,
                                            use_kernels=False)
        assert not hit
    assert len(segtiles._PLAN_CACHE) == 2
    assert segtiles.plan_cache_evictions() - base_ev == 2
    # LRU order: the two newest graphs are hits, the oldest was evicted
    cam, pt = graph(3)
    _, hit = segtiles.cached_dual_plans(cam, pt, 4, 16, use_kernels=False)
    assert hit
    cam, pt = graph(0)
    _, hit = segtiles.cached_dual_plans(cam, pt, 4, 16, use_kernels=False)
    assert not hit

    monkeypatch.setenv("MEGBA_PLAN_CACHE", "zero")
    with pytest.raises(ValueError, match="MEGBA_PLAN_CACHE"):
        segtiles.plan_cache_capacity()
    monkeypatch.setenv("MEGBA_PLAN_CACHE", "0")
    with pytest.raises(ValueError, match="MEGBA_PLAN_CACHE"):
        segtiles.plan_cache_capacity()
    monkeypatch.delenv("MEGBA_PLAN_CACHE")
    assert segtiles.plan_cache_capacity() == 8


@pytest.mark.slow
def test_solve_many_telemetry_reports_and_aggregate_cli(tmp_path,
                                                       monkeypatch):
    """Each fleet problem emits one SolveReport with a `fleet` block;
    the summarize --aggregate CLI renders status counts, throughput and
    latency percentiles from the stream."""
    sink = tmp_path / "fleet.jsonl"
    probs = [_mk(3, 32), _mk(7, 29)]
    opt = dataclasses.replace(OPT64, telemetry=str(sink))
    solve_many(probs, opt)

    from megba_tpu.observability.report import SolveReport

    lines = [l for l in sink.read_text().splitlines() if l.strip()]
    assert len(lines) == 2
    reps = [SolveReport.from_json(l) for l in lines]
    for rep in reps:
        assert rep.fleet["bucket"] == "c4_p32_e2048_float64"
        assert rep.fleet["lanes"] == 2
        assert rep.fleet["latency_s"] > 0
        assert rep.result["status_name"] in {"converged", "max_iter"}
        assert rep.fleet["stats"]["problems"] >= 2
    assert {rep.fleet["lane"] for rep in reps} == {0, 1}

    from megba_tpu.observability import summarize

    out = summarize.aggregate_paths([str(sink)])
    assert "fleet aggregate: 2 solves" in out
    assert "p50" in out and "p95" in out
    assert "bucket c4_p32_e2048_float64: 2 solves" in out

    # the CLI flag wires through main()
    rc = summarize.main(["--aggregate", str(sink)])
    assert rc == 0


def test_aggregate_reports_without_fleet_context():
    """--aggregate degrades gracefully on plain (non-fleet) report
    streams: latency falls back to the summed phase clock."""
    from megba_tpu.observability.report import SolveReport
    from megba_tpu.observability.summarize import aggregate_reports

    reps = [
        SolveReport(problem={}, config={}, backend={},
                    phases={"dispatch": {"total_s": 0.25, "calls": 1}},
                    result={"status_name": "converged"},
                    created_unix=100.0 + i)
        for i in range(3)
    ]
    out = aggregate_reports(reps)
    assert "3 solves" in out and "status converged: 3" in out
    assert "p50 250.0 ms" in out
    assert aggregate_reports([]) == "no reports"
