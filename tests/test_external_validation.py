"""External validation: our LM vs scipy.optimize.least_squares.

Self-consistency (PCG vs dense, autodiff vs analytical, shard counts)
cannot catch a systematically wrong objective or optimizer; an
independent trust-region solver on the identical residual can.
"""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.optimize import least_squares

from megba_tpu.algo import lm_solve
from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import bal_residual, make_residual_jacobian_fn


def test_final_cost_matches_scipy():
    s = make_synthetic_bal(num_cameras=4, num_points=24, obs_per_point=3,
                           seed=11, param_noise=2e-2, pixel_noise=0.3)
    nc, npts = 4, 24
    cam_idx, pt_idx = s.cam_idx, s.pt_idx
    obs = s.obs

    # --- scipy: flat parameter vector, vectorised residual via vmap ---
    batched = jax.jit(jax.vmap(bal_residual, in_axes=(0, 0, 0)))

    def residuals_flat(x):
        cams = jnp.asarray(x[: nc * 9].reshape(nc, 9))
        pts = jnp.asarray(x[nc * 9 :].reshape(npts, 3))
        r = batched(cams[cam_idx], pts[pt_idx], jnp.asarray(obs))
        return np.asarray(r).ravel()

    x0 = np.concatenate([s.cameras0.ravel(), s.points0.ravel()])
    scipy_res = least_squares(residuals_flat, x0, method="trf", xtol=1e-14,
                              ftol=1e-14, gtol=1e-12, max_nfev=400)
    scipy_cost = float(2.0 * scipy_res.cost)  # scipy cost = 1/2 sum r^2

    # --- ours ---
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=40, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(max_iter=300, tol=1e-16, refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    ours = lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(obs.T),
        jnp.asarray(cam_idx), jnp.asarray(pt_idx), jnp.ones(len(obs)), option)

    np.testing.assert_allclose(float(ours.cost), scipy_cost, rtol=1e-6)
    # And the initial costs must agree exactly (same objective).
    np.testing.assert_allclose(
        float(ours.initial_cost), float(np.sum(residuals_flat(x0) ** 2)),
        rtol=1e-12)
