"""Test configuration.

Default lane: everything on the CPU backend with 8 virtual devices (the
multi-device story the reference could never test — SURVEY.md §4) and
float64 enabled for numerical verification.

Opt-in hardware lane: `MEGBA_TPU_TESTS=1 pytest -m tpu` keeps the real
accelerator backend available and runs ONLY the `tpu`-marked suite
(tests/test_tpu.py) — serialized, foreground, f32.  Without the env var
the tpu marker is skipped and the whole process is pinned to CPU before
any backend init (the axon tunnel is single-client; a stray init from a
parallel unit test could wedge it).
"""

import os

import pytest

TPU_LANE = os.environ.get("MEGBA_TPU_TESTS") == "1"

if not TPU_LANE:
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

# The two-process suite split (scripts/run_tests.sh) works around an
# XLA:CPU compile-volume segfault observed on THIS jax/jaxlib build;
# surface a warning if the build changes so the workaround (and the
# single-process segfault note in README) gets re-validated rather than
# silently trusted.
_CALIBRATED_JAX = "0.9.0"
if jax.__version__ != _CALIBRATED_JAX:
    import warnings

    warnings.warn(
        f"test-infra calibrated on jax {_CALIBRATED_JAX}, running "
        f"{jax.__version__}: re-check the single-process XLA:CPU "
        "segfault workaround in scripts/run_tests.sh",
        stacklevel=1)

jax.config.update("jax_enable_x64", not TPU_LANE)

if not TPU_LANE:
    # The axon TPU plugin's register() overrides jax_platforms to
    # "axon,cpu" at interpreter startup (sitecustomize), stealing the
    # default device and — when the remote TPU tunnel is busy — hanging
    # backend init.  Backends initialize lazily, so forcing CPU here
    # (before any device query) keeps the whole suite off the TPU.
    jax.config.update("jax_platforms", "cpu")

# Persist compiled executables across runs — both lanes.  TPU: chip
# minutes are scarce, a tunnel window must measure, not recompile.  CPU:
# the suite's wall clock is dominated by XLA:CPU compiles of the full LM
# programs (the distributed/tiled parity tests each compile multi-second
# SPMD programs); caching them keeps the one-process tier-1 sweep inside
# its time budget on repeat runs and shaves the compile volume implicated
# in the backend_compile segfault (scripts/run_tests.sh).
from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

_cpus = jax.devices("cpu") if not TPU_LANE else []
if _cpus:
    jax.config.update("jax_default_device", _cpus[0])


def pytest_collection_modifyitems(config, items):
    for item in items:
        if "tpu" in item.keywords:
            if not TPU_LANE:
                item.add_marker(pytest.mark.skip(
                    reason="TPU lane disabled (set MEGBA_TPU_TESTS=1)"))
        elif TPU_LANE:
            item.add_marker(pytest.mark.skip(
                reason="TPU lane runs only -m tpu tests"))


def cpu_devices(n: int):
    assert len(_cpus) >= n, f"need {n} cpu devices, have {len(_cpus)}"
    return _cpus[:n]


@pytest.fixture
def retrace_sentinel():
    """Opt-in retrace guard (megba_tpu/analysis/retrace.py).

    Request this fixture and the test FAILS (at teardown) if the window
    saw an unexpected jit recompile: the same (site, static config,
    operand signature) traced twice — a jit cache bust.  Budget extra
    legitimate compiles with `retrace_sentinel.allow(...)`, or cap the
    total with `retrace_sentinel.max_compiles = n`.
    """
    from megba_tpu.analysis.retrace import sentinel

    with sentinel() as s:
        yield s
