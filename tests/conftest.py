"""Test configuration.

Runs everything on the CPU backend with 8 virtual devices (the
multi-device story the reference could never test — SURVEY.md §4) and
float64 enabled for numerical verification.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

# The axon TPU plugin's register() overrides jax_platforms to "axon,cpu" at
# interpreter startup (sitecustomize), stealing the default device and —
# when the remote TPU tunnel is busy — hanging backend init.  Backends
# initialize lazily, so forcing CPU here (before any device query) keeps
# the whole test suite off the TPU: unit tests are deterministic float64.
jax.config.update("jax_platforms", "cpu")

_cpus = jax.devices("cpu")
jax.config.update("jax_default_device", _cpus[0])


def cpu_devices(n: int):
    assert len(_cpus) >= n, f"need {n} cpu devices, have {len(_cpus)}"
    return _cpus[:n]
