"""Linear system + Schur-PCG tests vs dense direct solve (SURVEY.md §4c)."""

import os
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.common import ComputeKind, JacobianMode
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.linear_system import build_schur_system, damp_blocks, weight_system_inputs
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solver import dense_reference_solve, schur_pcg_solve


def build_test_system(seed=0, num_cameras=3, num_points=12, compute_kind=ComputeKind.IMPLICIT,
                      cam_fixed=None, pt_fixed=None):
    s = make_synthetic_bal(num_cameras=num_cameras, num_points=num_points, seed=seed)
    cams = jnp.asarray(s.cameras0.T)
    pts = jnp.asarray(s.points0.T)
    cam_idx = jnp.asarray(s.cam_idx)
    pt_idx = jnp.asarray(s.pt_idx)
    obs = jnp.asarray(s.obs.T)
    mask = jnp.ones(obs.shape[1])
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    r, Jc, Jp = f(cams[:, cam_idx], pts[:, pt_idx], obs)
    r, Jc, Jp = weight_system_inputs(r, Jc, Jp, cam_idx, pt_idx, mask,
                                     cam_fixed=cam_fixed, pt_fixed=pt_fixed)
    system = build_schur_system(
        r, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
        compute_kind=compute_kind, cam_fixed=cam_fixed, pt_fixed=pt_fixed)
    return system, r, Jc, Jp, cam_idx, pt_idx


@pytest.mark.parametrize("d", [1, 2, 3, 9])
def test_block_inv_matches_numpy(d):
    from megba_tpu.solver import block_inv
    r = np.random.default_rng(0)
    A = r.normal(size=(7, d, d))
    spd = A @ A.transpose(0, 2, 1) + 3.0 * np.eye(d)  # damped-SPD-like
    got = block_inv(jnp.asarray(spd))
    np.testing.assert_allclose(got, np.linalg.inv(spd), rtol=1e-9, atol=1e-11)


def test_hessian_blocks_match_dense_assembly():
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system()
    # Assemble J^T J brute-force per camera from the edge list (rows ->
    # per-edge [od, cd] blocks via reshape of the feature axis).
    nE = r.shape[1]
    for c in range(3):
        H = np.zeros((9, 9))
        g = np.zeros(9)
        for e in range(nE):
            if int(cam_idx[e]) == c:
                Je = np.asarray(Jc[:, e]).reshape(2, 9)
                H += Je.T @ Je
                g -= Je.T @ np.asarray(r[:, e])
        np.testing.assert_allclose(system.Hpp[c], H, rtol=1e-10, atol=1e-12)
        np.testing.assert_allclose(system.g_cam[:, c], g, rtol=1e-10, atol=1e-12)


def test_damping():
    system, *_ = build_test_system()
    region = jnp.asarray(10.0)
    damped = damp_blocks(system.Hpp, region)
    expect = np.asarray(system.Hpp).copy()
    for i in range(expect.shape[0]):
        np.fill_diagonal(expect[i], np.diag(expect[i]) * 1.1)
    np.testing.assert_allclose(damped, expect, rtol=1e-12)


@pytest.mark.parametrize("compute_kind", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_pcg_matches_dense(compute_kind):
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(compute_kind=compute_kind)
    region = jnp.asarray(100.0)
    dx_cam_d, dx_pt_d = dense_reference_solve(system, Jc, Jp, cam_idx, pt_idx, region)
    out = schur_pcg_solve(
        system, Jc, Jp, cam_idx, pt_idx, region,
        max_iter=500, tol=1e-14, refuse_ratio=1e30, compute_kind=compute_kind)
    np.testing.assert_allclose(out.dx_cam, dx_cam_d, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(out.dx_pt, dx_pt_d, rtol=1e-6, atol=1e-8)


def test_pcg_jit_compiles():
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system()
    f = jax.jit(
        lambda sys_, Jc_, Jp_, region: schur_pcg_solve(
            sys_, Jc_, Jp_, cam_idx, pt_idx, region, max_iter=50, tol=1e-10,
        )
    )
    out = f(system, Jc, Jp, jnp.asarray(50.0))
    assert np.all(np.isfinite(out.dx_cam)) and np.all(np.isfinite(out.dx_pt))
    assert int(out.iterations) > 0


@pytest.mark.parametrize("compute_kind", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_pcg_mixed_precision_close_to_full(compute_kind):
    # bf16 coupling products with f32 accumulation (BASELINE.md config 5)
    # must land near the full-precision solution.
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(compute_kind=compute_kind)
    region = jnp.asarray(100.0)
    full = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                           max_iter=200, tol=1e-12, refuse_ratio=1e30,
                           compute_kind=compute_kind)
    mixed = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                            max_iter=200, tol=1e-12, refuse_ratio=1e30,
                            compute_kind=compute_kind, mixed_precision=True)
    assert mixed.dx_cam.dtype == full.dx_cam.dtype  # Krylov state stays full precision
    # bf16 coupling products give an inexact Newton step (LM's accept /
    # reject absorbs this); require direction agreement, not equality.
    scale = float(jnp.max(jnp.abs(full.dx_cam)))
    np.testing.assert_allclose(mixed.dx_cam, full.dx_cam, atol=0.25 * scale)
    cos = float(jnp.sum(mixed.dx_cam * full.dx_cam)) / (
        float(jnp.linalg.norm(mixed.dx_cam)) * float(jnp.linalg.norm(full.dx_cam)))
    assert cos > 0.95


@pytest.mark.parametrize("compute_kind", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_schur_diag_preconditioner(compute_kind):
    # A preconditioner must not change WHAT PCG converges to, only how it
    # gets there: SCHUR_DIAG's solution matches the dense direct solve.
    # (Iteration counts are problem-dependent — see PreconditionerKind.)
    from megba_tpu.common import PreconditionerKind
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(
        seed=3, compute_kind=compute_kind)
    region = jnp.asarray(100.0)
    kw = dict(max_iter=500, tol=1e-13, tol_relative=True, refuse_ratio=1e30,
              compute_kind=compute_kind)
    sd = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                         preconditioner=PreconditionerKind.SCHUR_DIAG, **kw)
    dx_cam_d, dx_pt_d = dense_reference_solve(system, Jc, Jp, cam_idx, pt_idx, region)
    np.testing.assert_allclose(sd.dx_cam, dx_cam_d, rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(sd.dx_pt, dx_pt_d, rtol=1e-5, atol=1e-8)
    assert int(sd.iterations) > 0


def test_relative_tolerance_mode():
    # tol_relative reinterprets tol as a fraction of rho0: a modest 1e-8
    # relative tolerance must reach (near) the dense answer regardless of
    # the problem's cost scale, where the same 1e-8 ABSOLUTE tol would
    # run to max_iter on a large-scale problem or quit instantly on a
    # tiny one.
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(seed=5)
    region = jnp.asarray(100.0)
    dx_cam_d, dx_pt_d = dense_reference_solve(system, Jc, Jp, cam_idx, pt_idx, region)
    out = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                          max_iter=500, tol=1e-12, tol_relative=True,
                          refuse_ratio=1e30)
    np.testing.assert_allclose(out.dx_cam, dx_cam_d, rtol=1e-4, atol=1e-7)
    # With an absurd absolute tol the loop would exit immediately; the
    # relative mode must actually iterate.
    assert int(out.iterations) > 0


def test_refuse_ratio_guard():
    # With the reference's default refuse_ratio=1.0, the solver must stop
    # as soon as rho is non-decreasing and restore the best iterate
    # (schur_pcg_solver.cu:288-296 semantics) — fewer iterations than the
    # effectively-disabled guard, and still a usable descent direction.
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(seed=4)
    region = jnp.asarray(1e3)
    guarded = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                              max_iter=300, tol=1e-30, refuse_ratio=1.0)
    free = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                           max_iter=300, tol=1e-30, refuse_ratio=1e30)
    # Strictly fewer: in this seeded scenario the guard fires at ~9 vs 40
    # unguarded iterations, so equality would mean the guard is broken.
    assert int(guarded.iterations) < int(free.iterations)
    assert np.all(np.isfinite(guarded.dx_cam))
    # The guarded solution still reduces the quadratic model vs dx=0:
    # g^T dx > 0 for a descent direction of 1/2 x^T H x - g^T x.
    descent = float(jnp.sum(system.g_cam * guarded.dx_cam)
                    + jnp.sum(system.g_pt * guarded.dx_pt))
    assert descent > 0


def test_fixed_camera_gets_zero_update():
    cam_fixed = jnp.asarray([True, False, False])
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(cam_fixed=cam_fixed)
    out = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, jnp.asarray(100.0),
                          max_iter=300, tol=1e-13, refuse_ratio=1e30)
    np.testing.assert_allclose(out.dx_cam[:, 0], np.zeros(9), atol=1e-12)
    assert float(jnp.max(jnp.abs(out.dx_cam[:, 1:]))) > 0


def test_edgeless_vertex_is_inert_not_nan():
    # A point with no observations (possible in filtered real datasets)
    # must get a zero update, not NaN-poison the solve.
    s = make_synthetic_bal(num_cameras=3, num_points=12, seed=2)
    cams, pts0 = jnp.asarray(s.cameras0.T), np.asarray(s.points0)
    pts = jnp.asarray(np.concatenate([pts0, [[9.0, 9.0, 9.0]]]).T)  # orphan point 12
    cam_idx, pt_idx, obs = jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx), jnp.asarray(s.obs.T)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    r, Jc, Jp = f(cams[:, cam_idx], pts[:, pt_idx], obs)
    r, Jc, Jp = weight_system_inputs(r, Jc, Jp, cam_idx, pt_idx, jnp.ones(len(s.obs)))
    system = build_schur_system(r, Jc, Jp, cam_idx, pt_idx, 3, 13)
    out = schur_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, jnp.asarray(100.0),
                          max_iter=300, tol=1e-13, refuse_ratio=1e30)
    assert np.all(np.isfinite(out.dx_cam)) and np.all(np.isfinite(out.dx_pt))
    np.testing.assert_allclose(out.dx_pt[:, 12], np.zeros(3), atol=1e-14)


def test_padding_edges_are_inert():
    # Same system with 5 extra masked edges must produce identical blocks.
    s = make_synthetic_bal(num_cameras=3, num_points=12, seed=1)
    cams, pts = jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    def build(cam_idx, pt_idx, obs, mask):
        r, Jc, Jp = f(cams[:, cam_idx], pts[:, pt_idx], obs)
        r, Jc, Jp = weight_system_inputs(r, Jc, Jp, cam_idx, pt_idx, mask)
        return build_schur_system(r, Jc, Jp, cam_idx, pt_idx, 3, 12)

    base = build(jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx), jnp.asarray(s.obs.T),
                 jnp.ones(len(s.obs)))
    pad = 5
    cam_idx_p = jnp.concatenate([jnp.asarray(s.cam_idx), jnp.zeros(pad, jnp.int32)])
    pt_idx_p = jnp.concatenate([jnp.asarray(s.pt_idx), jnp.zeros(pad, jnp.int32)])
    obs_p = jnp.concatenate([jnp.asarray(s.obs.T), jnp.full((2, pad), 123.0)], axis=1)
    mask_p = jnp.concatenate([jnp.ones(len(s.obs)), jnp.zeros(pad)])
    padded = build(cam_idx_p, pt_idx_p, obs_p, mask_p)
    np.testing.assert_allclose(padded.Hpp, base.Hpp, rtol=1e-12)
    np.testing.assert_allclose(padded.Hll, base.Hll, rtol=1e-12)
    np.testing.assert_allclose(padded.g_cam, base.g_cam, rtol=1e-12)
    np.testing.assert_allclose(padded.g_pt, base.g_pt, rtol=1e-12)


@pytest.mark.slow
def test_mixed_precision_validation_pipeline(tmp_path):
    """End-to-end run of scripts/mixed_precision_validation.py at small
    scale: bf16-coupling PCG must reach the f32 optimum (rel tol 1e-3)
    and the script must exit 0 and write its artifact (VERDICT r04
    item 5 — config 5 becomes a pure bench run when hardware answers)."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["MEGBA_MP_CONFIG"] = "venice"
    env["MEGBA_BENCH_SCALE"] = "0.02"
    out_path = str(tmp_path / "mp.json")
    env["MEGBA_MP_OUT"] = out_path  # keep the full-scale artifact intact
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "mixed_precision_validation.py")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=600)
    assert p.returncode == 0, p.stdout + p.stderr
    payload = json.loads(open(out_path).read())
    assert payload["pass"] is True
    assert "bf16_coupling" in payload["runs"] and "f32" in payload["runs"]
