"""megba_tpu/analysis/: linter rules, retrace sentinel, strict lane.

Every lint rule gets a positive (fires on the seeded bad fixture) AND a
negative (silent on the good fixture) test, so a rule that silently
stops matching — or starts over-matching — breaks this suite rather
than the codebase.  The retrace sentinel is exercised against a real
deliberately shape-unstable solve loop, and the strict-promotion lane
runs the small solve smoke in-process.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "lint_fixtures")
BAD = os.path.join(FIXTURES, "bad_patterns.py")
GOOD = os.path.join(FIXTURES, "good_patterns.py")
PACKAGE = os.path.join(os.path.dirname(__file__), "..", "megba_tpu")


def _lint(*paths, rules=None):
    from megba_tpu.analysis.lint import lint_paths

    return lint_paths(list(paths), rules=rules)


# ------------------------------------------------------------ lint rules


def test_lint_clean_on_package():
    """THE acceptance gate: the package itself carries no violations."""
    findings = _lint(PACKAGE)
    assert findings == [], "\n".join(f.format() for f in findings)


@pytest.mark.parametrize("rule", [
    "host-callback", "np-in-jit", "implicit-dtype", "scalar-promotion",
    "donated-reuse", "weak-literal", "raw-clock"])
def test_each_rule_fires_on_bad_and_not_on_good(rule):
    bad = _lint(BAD, rules=[rule])
    assert bad, f"rule {rule} found nothing in the seeded bad fixture"
    assert all(f.rule == rule for f in bad)
    good = _lint(GOOD, rules=[rule])
    assert good == [], "\n".join(f.format() for f in good)


def test_bad_fixture_finding_shape():
    """Pin the exact per-rule hit counts in the seeded fixture, so both
    silent rule decay and over-matching regress loudly."""
    from collections import Counter

    counts = Counter(f.rule for f in _lint(BAD))
    assert counts == {
        "host-callback": 3,     # debug.callback, debug.print, io_callback
        "np-in-jit": 5,         # np call, float(), .item(), np.sqrt via
                                # reachability, np.float64 in promoting_math
        "implicit-dtype": 6,    # zeros/ones/arange/array/full/eye
        "scalar-promotion": 2,  # np.float64 *, jnp.int64 +
        "donated-reuse": 1,
        "weak-literal": 5,      # 3 where branches + 2 clip bounds
        "raw-clock": 3,         # time.time, time.perf_counter, aliased
    }, counts


def test_pragma_suppresses_single_line(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    a = jnp.zeros(n)\n"
        "    b = jnp.zeros(n)  # megba: allow-implicit-dtype\n"
        "    return a, b\n")
    findings = _lint(str(src))
    assert len(findings) == 1 and findings[0].line == 3


def test_jit_entry_pragma_extends_reachability(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "import numpy as np\n"
        "def helper(x):\n"
        "    return np.sqrt(x)\n"
        "def engine(x):  # megba: jit-entry\n"
        "    return helper(x)\n"
        "def host_only(x):\n"
        "    return np.sqrt(x)\n")
    findings = _lint(str(src), rules=["np-in-jit"])
    # helper is reachable through engine; host_only is not reachable
    assert [f.line for f in findings] == [3]


def test_callgraph_detects_repo_entry_points():
    """The real builders must be recognised: decorated partial(jax.jit),
    jax.jit(fn, ...), shard_map(fn, ...), and the jit-entry pragma."""
    from megba_tpu.analysis.callgraph import PackageIndex

    idx = PackageIndex.build([PACKAGE])
    entries = {q for q, f in idx.functions.items() if f.is_entry}
    assert "megba_tpu.solve._build_single_solve.fn" in entries
    assert "megba_tpu.parallel.mesh._build_sharded_solve.fn" in entries
    assert "megba_tpu.models.pgo._pgo_program.run" in entries
    assert "megba_tpu.ops.residuals.bal_residual" in entries  # pragma
    # and the hot inner layers are reachable from them
    for q in ("megba_tpu.algo.lm.lm_solve",
              "megba_tpu.solver.pcg.schur_pcg_solve",
              "megba_tpu.solver.pcg.plain_pcg_solve",
              "megba_tpu.linear_system.builder.build_schur_system",
              "megba_tpu.ops.robust.robustify"):
        assert q in idx.reachable, q


def test_cli_exit_codes():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    root = os.path.dirname(PACKAGE)
    bad = subprocess.run(
        [sys.executable, "-m", "megba_tpu.analysis.lint", BAD],
        capture_output=True, text=True, timeout=120, cwd=root, env=env)
    assert bad.returncode == 1, bad.stderr
    assert "host-callback" in bad.stdout and "donated-reuse" in bad.stdout
    good = subprocess.run(
        [sys.executable, "-m", "megba_tpu.analysis.lint", GOOD,
         "--list-rules"],
        capture_output=True, text=True, timeout=120, cwd=root, env=env)
    assert good.returncode == 0, good.stderr
    none = subprocess.run(
        [sys.executable, "-m", "megba_tpu.analysis.lint"],
        capture_output=True, text=True, timeout=120, cwd=root, env=env)
    assert none.returncode == 2
    # A vanished target must FAIL the gate (exit 2), not lint zero
    # files and report clean — a typo'd path in scripts/lint.sh would
    # otherwise silently disarm the whole acceptance gate.
    gone = subprocess.run(
        [sys.executable, "-m", "megba_tpu.analysis.lint",
         "no_such_dir_xyz/"],
        capture_output=True, text=True, timeout=120, cwd=root, env=env)
    assert gone.returncode == 2, (gone.stdout, gone.stderr)
    assert "not a directory" in gone.stderr


def test_list_suppressions(tmp_path, capsys):
    # The accumulated-suppressions audit trail: every real inline
    # `# megba: allow-<rule>` pragma is listed with file:line; prose
    # mentions of the pragma syntax (docstrings) are not suppressions.
    from megba_tpu.analysis.lint import list_suppressions, run_lint

    mod = tmp_path / "suppressed.py"
    mod.write_text(
        '"""Mentions `# megba: allow-<rule>` in prose only."""\n'
        "import numpy as np\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    n = np.prod(x.shape)  # megba: allow-np-in-jit\n"
        "    return x * n\n")
    found = list_suppressions([str(mod)])
    assert [(line, allows) for _, line, allows, _ in found] == [
        (6, ["allow-np-in-jit"])]

    rc = run_lint(["--list-suppressions", str(mod)])
    out = capsys.readouterr()
    assert rc == 0
    assert f"{mod}:6: allow-np-in-jit" in out.out
    assert "1 suppression(s)" in out.err
    # The good fixture's one real pragma is found through the same path.
    found_good = list_suppressions([GOOD])
    assert any("allow-np-in-jit" in allows for _, _, allows, _ in found_good)


# -------------------------------------------------------------- retrace


def _tiny_option(**kw):
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption

    return ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=2),
        # distinctive tolerances: a config no other suite compiles, so
        # these programs are always fresh compiles inside the window
        solver_option=SolverOption(max_iter=3, tol=3.7e-9), **kw)


def _tiny_solve(num_cameras, option):
    from megba_tpu.common import JacobianMode
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    s = make_synthetic_bal(num_cameras=num_cameras, num_points=23,
                           obs_per_point=3, seed=1, dtype=np.float32)
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    return flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                      s.pt_idx, option)


def test_retrace_sentinel_quiet_on_cache_hit(retrace_sentinel):
    """Two identical solves = one compile; the opt-in fixture passes."""
    option = _tiny_option()
    _tiny_solve(6, option)
    before = retrace_sentinel.total_new()
    assert before > 0  # the first solve really did trace
    _tiny_solve(6, option)
    assert retrace_sentinel.total_new() == before  # jit cache hit


def test_retrace_sentinel_catches_shape_unstable_loop():
    """A loop that grows the problem each call compiles per iteration —
    exactly the silent-retrace failure mode the sentinel exists for."""
    from megba_tpu.analysis.retrace import RetraceError, sentinel

    option = _tiny_option()
    with pytest.raises(RetraceError, match="shape-unstable"):
        with sentinel(max_compiles=4) as s:
            for nc in (7, 9, 11):  # three signatures, >= 9 traces
                _tiny_solve(nc, option)


def test_retrace_sentinel_counts_per_signature():
    from megba_tpu.analysis.retrace import sentinel

    option = _tiny_option(use_schur=False)  # distinct config
    with sentinel() as s:
        _tiny_solve(6, option)
        new = s.new_compiles()
    sites = {k[0] for k in new}
    assert {"solve.single", "algo.lm_solve", "solver.plain_pcg"} <= sites
    assert all(count == 1 for count in new.values())


def test_retrace_duplicate_detection_and_allow():
    """A second trace of an identical (site, static, signature) is the
    cache-bust signal; `allow(duplicates=...)` budgets legitimate ones."""
    from megba_tpu.analysis.retrace import (
        RetraceError, note_trace, sentinel)

    class FakeAval:
        shape = (3, 4)
        dtype = "float32"

    with pytest.raises(RetraceError, match="retrace"):
        with sentinel() as s:
            note_trace("test.dup", FakeAval(), static="cfg", force=True)
            note_trace("test.dup", FakeAval(), static="cfg", force=True)

    with sentinel() as s:
        note_trace("test.dup2", FakeAval(), static="cfg", force=True)
        note_trace("test.dup2", FakeAval(), static="cfg", force=True)
        s.allow(duplicates=1)


def test_note_trace_ignores_eager_calls():
    """Eager (non-jit) executions of instrumented layers are NOT
    compilations: two identical eager lm_solve/pcg-style calls must not
    read as a duplicate-signature cache bust (lm_solve is supported
    eagerly — e.g. tests/test_lm.py calls it without jit)."""
    import jax.numpy as jnp

    from megba_tpu.analysis.retrace import note_trace, sentinel

    x = jnp.ones((2, 3), jnp.float32)
    with sentinel() as s:
        note_trace("test.eager", x, static="cfg")
        note_trace("test.eager", x, static="cfg")
        assert s.total_new() == 0  # guard filtered both; exit is quiet


def test_static_key_closure_identity_is_qualname():
    """Two closures of one factory produce the SAME static key — the
    property that makes rebuilt-per-call programs show as duplicates."""
    from megba_tpu.analysis.retrace import static_key

    def factory():
        def engine(x):
            return x

        return engine

    assert static_key(factory()) == static_key(factory())
    assert static_key(factory(), 1, "a") != static_key(factory(), 2, "a")


# ---------------------------------------------------------- strict lane


def test_strict_promotion_context_restores_config():
    import jax

    from megba_tpu.analysis.strict_dtype import strict_promotion

    before = (jax.config.jax_numpy_dtype_promotion, jax.config.jax_debug_nans)
    with strict_promotion():
        assert jax.config.jax_numpy_dtype_promotion == "strict"
        assert jax.config.jax_debug_nans
    assert (jax.config.jax_numpy_dtype_promotion,
            jax.config.jax_debug_nans) == before


def test_strict_lane_ba_and_pgo_smoke():
    """The real solve pipelines must trace clean under strict promotion
    + debug-nans (the dynamic half of the sanitizer lane; scripts/lint.sh
    runs the same smoke as a subprocess gate)."""
    from megba_tpu.analysis.strict_dtype import (
        run_ba_smoke, run_pgo_smoke, strict_promotion)

    with strict_promotion():
        res = run_ba_smoke(dtype=np.float32)
        assert float(res.cost) < float(res.initial_cost)
        pgo = run_pgo_smoke(dtype=np.float32)
        assert float(pgo.cost) < float(pgo.initial_cost)


def test_strict_promotion_actually_bites():
    """Sanity that the lane is not a no-op: a mixed-dtype op that strict
    mode must reject really raises inside the context."""
    import jax.numpy as jnp

    from megba_tpu.analysis.strict_dtype import strict_promotion

    a = jnp.ones(3, jnp.float32)
    b = jnp.ones(3, jnp.bfloat16)
    with strict_promotion(debug_nans=False):
        with pytest.raises(Exception, match="[Pp]romotion"):
            _ = a + b
