"""Pose-graph optimization family: geometry, convergence, validation.

Capability beyond the reference (MegBA's edge is hard-wired to one
camera + one landmark; same-kind between-factors are inexpressible
there).  Verified the same way the BA family is: exact-geometry unit
checks, end-to-end convergence on a drifted loop-closure graph, gauge
handling, and an external anchor against scipy.least_squares on the
identical objective.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
from megba_tpu.models.pgo import (
    between_residual,
    make_synthetic_pose_graph,
    solve_pgo,
)
from megba_tpu.ops import geo


def _option(max_iter=30):
    return ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-12,
                               epsilon2=1e-15),
        solver_option=SolverOption(max_iter=120, tol=1e-14,
                                   refuse_ratio=1e30),
    )


def test_log_map_roundtrip():
    rng = np.random.default_rng(0)
    aas = np.concatenate([
        rng.standard_normal((50, 3)) * 0.9,  # angle < pi: exact roundtrip
        rng.standard_normal((20, 3)) * 1e-7,  # small-angle branch
        np.zeros((1, 3)),
    ])
    rt = jax.vmap(lambda a: geo.rotation_matrix_to_angle_axis(
        geo.angle_axis_to_rotation_matrix(a)))(jnp.asarray(aas))
    np.testing.assert_allclose(np.asarray(rt), aas, atol=1e-9)
    # Above pi the log returns the principal branch: R must round-trip.
    big = rng.standard_normal((30, 3)) * 3.0
    R1 = jax.vmap(geo.angle_axis_to_rotation_matrix)(jnp.asarray(big))
    R2 = jax.vmap(lambda R: geo.angle_axis_to_rotation_matrix(
        geo.rotation_matrix_to_angle_axis(R)))(R1)
    np.testing.assert_allclose(np.asarray(R1), np.asarray(R2), atol=1e-9)
    # Autodiff through the log map stays finite (the PGO Jacobian path).
    J = jax.vmap(jax.jacfwd(lambda a: geo.rotation_matrix_to_angle_axis(
        geo.angle_axis_to_rotation_matrix(a))))(jnp.asarray(aas))
    assert bool(np.all(np.isfinite(np.asarray(J))))


def test_residual_zero_at_ground_truth():
    g = make_synthetic_pose_graph(num_poses=24, loop_closures=5)
    r = jax.vmap(between_residual)(
        jnp.asarray(g.poses_gt)[g.edge_i],
        jnp.asarray(g.poses_gt)[g.edge_j],
        jnp.asarray(g.meas))
    assert float(jnp.max(jnp.abs(r))) < 1e-9


def test_pgo_converges_and_respects_gauge():
    g = make_synthetic_pose_graph(num_poses=32, loop_closures=6,
                                  drift_noise=0.05)
    res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, _option())
    assert float(res.cost) < 1e-9 * max(float(res.initial_cost), 1.0)
    # Gauge anchor: pose 0 (fixed by default) must not move.
    np.testing.assert_array_equal(np.asarray(res.poses)[0], g.poses0[0])
    # Recovered trajectory matches ground truth AS SE(3) ELEMENTS.  The
    # angle-axis chart is not unique: gt poses with |theta| > pi come
    # back on the principal branch (2*pi away in coordinates), so
    # compare rotation matrices + translations, not raw coordinates.
    poses = np.asarray(res.poses)
    R_rec = jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(poses[:, :3]))
    R_gt = jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(g.poses_gt[:, :3]))
    np.testing.assert_allclose(np.asarray(R_rec), np.asarray(R_gt),
                               atol=5e-5)
    np.testing.assert_allclose(poses[:, 3:], g.poses_gt[:, 3:], atol=5e-5)


def test_pgo_with_information_matrix():
    g = make_synthetic_pose_graph(num_poses=20, loop_closures=4,
                                  drift_noise=0.04, seed=3)
    si = np.tile(np.eye(6) * 2.0, (len(g.edge_i), 1, 1))
    res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, _option(),
                    sqrt_info=si)
    # L = 2I scales every residual by 2, cost by 4; convergence holds.
    assert float(res.cost) < 1e-9
    res_plain = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas,
                          _option(max_iter=0))
    res_si = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas,
                       _option(max_iter=0), sqrt_info=si)
    np.testing.assert_allclose(
        float(res_si.initial_cost), 4.0 * float(res_plain.initial_cost),
        rtol=1e-9)


def test_pgo_matches_scipy():
    from scipy.optimize import least_squares

    g = make_synthetic_pose_graph(num_poses=12, loop_closures=3,
                                  drift_noise=0.08, meas_noise=0.02,
                                  seed=7)
    n = g.poses_gt.shape[0]

    batched = jax.jit(jax.vmap(between_residual))
    meas_j = jnp.asarray(g.meas)
    ei, ej = g.edge_i, g.edge_j

    def residuals_flat(x):
        poses = jnp.asarray(
            np.concatenate([g.poses0[:1].ravel(), x]).reshape(n, 6))
        r = batched(poses[ei], poses[ej], meas_j)
        return np.asarray(r).ravel()

    x0 = g.poses0[1:].ravel()  # pose 0 fixed, as in solve_pgo's default
    sp = least_squares(residuals_flat, x0, method="trf", xtol=1e-14,
                       ftol=1e-14, gtol=1e-12, max_nfev=300)
    scipy_cost = float(2.0 * sp.cost)

    res = solve_pgo(g.poses0, ei, ej, g.meas, _option(max_iter=60))
    np.testing.assert_allclose(float(res.cost), scipy_cost, rtol=1e-5)


def test_pgo_sharded_matches_single():
    """world_size 2/8 on the virtual CPU mesh == single device.

    The PGO family's distributed lowering (solve_pgo pads + shards the
    edge axis, psums at cost/gradient/diag/matvec — the same replicate-
    parameters scheme as the BA path, SURVEY.md 2.3).  29 poses / 34
    edges is NOT divisible by 2 or 8, so the padding/mask path is
    exercised too.
    """
    g = make_synthetic_pose_graph(num_poses=29, loop_closures=6,
                                  drift_noise=0.05, seed=11)

    def opt(world):
        o = _option(max_iter=12)
        import dataclasses

        return dataclasses.replace(o, world_size=world)

    res1 = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, opt(1))
    si = np.tile(np.eye(6) * 1.5, (len(g.edge_i), 1, 1))
    res1_si = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, opt(1),
                        sqrt_info=si)
    for world in (2, 8):
        res_w = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, opt(world))
        np.testing.assert_allclose(float(res_w.cost), float(res1.cost),
                                   rtol=1e-9, atol=1e-18)
        assert int(res_w.iterations) == int(res1.iterations)
        np.testing.assert_allclose(np.asarray(res_w.poses),
                                   np.asarray(res1.poses), atol=1e-7)
        res_w_si = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas,
                             opt(world), sqrt_info=si)
        np.testing.assert_allclose(float(res_w_si.cost),
                                   float(res1_si.cost), rtol=1e-9,
                                   atol=1e-18)


def test_pgo_robust_rejects_outlier_loop_closure():
    """Huber/Cauchy IRLS on the PGO family (loop-closure outliers are
    THE classic robust-PGO setting; ops/robust.py, same scheme as the
    BA loop)."""
    import dataclasses

    g = make_synthetic_pose_graph(num_poses=24, loop_closures=5,
                                  drift_noise=0.04, seed=13)
    # Corrupt one loop closure (the last edge) with a gross translation.
    meas_bad = g.meas.copy()
    meas_bad[-1, 3:] += np.array([4.0, -3.0, 2.0])

    from megba_tpu.ops.robust import RobustKind

    def solve(kind, delta=0.1):
        opt = dataclasses.replace(_option(max_iter=40),
                                  robust_kind=kind, robust_delta=delta)
        return solve_pgo(g.poses0, g.edge_i, g.edge_j, meas_bad, opt)

    def max_err(res):
        # Translation error only: chart-free and dominated by the
        # outlier's pull.
        return float(np.max(np.linalg.norm(
            np.asarray(res.poses)[:, 3:] - g.poses_gt[:, 3:], axis=1)))

    err_plain = max_err(solve(RobustKind.NONE))
    err_huber = max_err(solve(RobustKind.HUBER))
    err_cauchy = max_err(solve(RobustKind.CAUCHY))
    # The outlier drags the non-robust solution far off ground truth
    # (~3.6 on a radius-1 circle).  Huber's linear tail still lets it
    # pull a little (the known Huber property); redescending Cauchy
    # suppresses it almost entirely.
    assert err_plain > 2.0, err_plain
    assert err_huber < err_plain / 10, (err_plain, err_huber)
    assert err_cauchy < 0.05, err_cauchy

    # Robust + sharded compose: world 8 matches world 1 exactly.
    opt8 = dataclasses.replace(_option(max_iter=12), world_size=8,
                               robust_kind=RobustKind.HUBER,
                               robust_delta=0.1)
    opt1 = dataclasses.replace(opt8, world_size=1)
    r1 = solve_pgo(g.poses0, g.edge_i, g.edge_j, meas_bad, opt1)
    r8 = solve_pgo(g.poses0, g.edge_i, g.edge_j, meas_bad, opt8)
    np.testing.assert_allclose(float(r8.cost), float(r1.cost), rtol=1e-9)


def test_spanning_tree_init():
    """BFS bootstrap from measurements (models/pgo.spanning_tree_init).

    Exact on noise-free odometry; recovers from garbage initial poses
    (the standard g2o-practitioner bootstrap for exports with missing
    VERTEX estimates)."""
    from megba_tpu.models.pgo import spanning_tree_init

    g = make_synthetic_pose_graph(num_poses=20, loop_closures=4,
                                  meas_noise=0.0, seed=15)
    rng = np.random.default_rng(0)
    garbage = rng.standard_normal((20, 6)) * 3.0
    garbage[0] = g.poses_gt[0]  # the anchor keeps its pose

    init = spanning_tree_init(garbage, g.edge_i, g.edge_j, g.meas)
    # Noise-free measurements + anchor at gt -> the tree init IS the
    # ground truth (as SE(3) elements).
    R_init = jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(init[:, :3]))
    R_gt = jax.vmap(geo.angle_axis_to_rotation_matrix)(
        jnp.asarray(g.poses_gt[:, :3]))
    np.testing.assert_allclose(np.asarray(R_init), np.asarray(R_gt),
                               atol=1e-9)
    np.testing.assert_allclose(init[:, 3:], g.poses_gt[:, 3:], atol=1e-9)

    # End-to-end through the g2o route: garbage file estimates +
    # spanning-tree init converge; trusting the file does not (within
    # the same budget).
    import io as _io

    from megba_tpu.io.g2o import G2OGraph, solve_g2o, write_g2o

    graph = G2OGraph(
        poses=garbage, edge_i=g.edge_i, edge_j=g.edge_j, meas=g.meas,
        info=np.tile(np.eye(6), (len(g.edge_i), 1, 1)),
        fixed=np.array([True] + [False] * 19),
        ids=np.arange(20, dtype=np.int64))
    buf = _io.StringIO()
    write_g2o(buf, graph)
    _, res = solve_g2o(_io.StringIO(buf.getvalue()), _option(max_iter=10),
                       init="spanning_tree")
    assert float(res.cost) < 1e-12

    # Disconnected poses keep their estimate (no NaNs, no crash).
    ei = np.array([0, 1], np.int32)
    ej = np.array([1, 2], np.int32)
    init2 = spanning_tree_init(garbage[:5], ei, ej, g.meas[:2])
    np.testing.assert_array_equal(init2[3:], garbage[3:5])


@pytest.mark.slow
def test_pgo_sharded_matches_single_at_scale():
    """World-8 parity at non-degenerate scale (5k poses / ~6.2k edges):
    real padding remainders, thousands of segments per shard."""
    import dataclasses

    g = make_synthetic_pose_graph(num_poses=5000, loop_closures=1200,
                                  drift_noise=0.01, seed=17)

    def opt(world):
        return dataclasses.replace(_option(max_iter=6), world_size=world)

    res1 = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, opt(1))
    res8 = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, opt(8))
    np.testing.assert_allclose(float(res8.cost), float(res1.cost),
                               rtol=1e-9)
    assert int(res8.iterations) == int(res1.iterations)
    np.testing.assert_allclose(np.asarray(res8.poses),
                               np.asarray(res1.poses), atol=1e-8)


def test_prior_factors_anchor_the_solution():
    """with_priors (the reference's own TODO — 'prior factor (TBD)'):
    a strong prior on one pose anchors the whole graph at that pose's
    prior value; the virtual anchor poses come back unchanged."""

    from megba_tpu.models.pgo import (
        make_synthetic_pose_graph, solve_pgo, spanning_tree_init,
        with_priors)

    g = make_synthetic_pose_graph(num_poses=20, loop_closures=5, seed=4)
    n = g.poses0.shape[0]
    # Prior: pose 3 belongs at a shifted location (no FIX anywhere —
    # the prior itself is the gauge).
    target = g.poses_gt[3] + np.array([0, 0, 0, 0.5, -0.25, 0.1])
    poses0, ei, ej, meas, fixed, si = with_priors(
        g.poses0, g.edge_i, g.edge_j, g.meas,
        prior_idx=[3], prior_poses=[target])
    assert poses0.shape[0] == n + 1 and fixed[n] and not fixed[:n].any()
    # Canonical flow: the prior's virtual anchor seeds the spanning-tree
    # bootstrap (BFS roots at fixed poses), which places the whole graph
    # consistently with the prior; LM then polishes.  Without the
    # bootstrap the drifted init can LM-converge into a genuine local
    # minimum of the rotation manifold (observed: cost 2.1e-2 with a
    # near-zero gradient) — priors change the basin, not the solver.
    poses0 = spanning_tree_init(poses0, ei, ej, meas, fixed)
    option = ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=80, epsilon1=1e-14, epsilon2=1e-16),
        solver_option=SolverOption(max_iter=80, tol=1e-14),
    )
    res = solve_pgo(poses0, ei, ej, meas, option, sqrt_info=si, fixed=fixed)
    out = np.asarray(res.poses)
    # The anchored pose sits at its prior (interior measurements are
    # noise-free, so the prior and the graph agree up to the shift).
    np.testing.assert_allclose(out[3], target, atol=1e-6)
    # Virtual anchor pose untouched.
    np.testing.assert_allclose(out[n], target, atol=0)
    # The whole graph followed the prior: relative poses still satisfy
    # the measurements (cost ~ 0 despite the global shift).
    assert float(res.cost) < 1e-10


def test_prior_factor_weighting_trades_off():
    """With measurement-vs-prior conflict, the prior's information
    matrix controls the trade: a huge prior weight pins the pose, a
    tiny one defers to the odometry."""
    from megba_tpu.models.pgo import (
        make_synthetic_pose_graph, solve_pgo, with_priors)

    g = make_synthetic_pose_graph(num_poses=8, loop_closures=2, seed=9)
    n = g.poses0.shape[0]
    # Conflicting prior: pose 5 pulled 1m off its true position.
    target = g.poses_gt[5] + np.array([0, 0, 0, 1.0, 0, 0])
    option = ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=25, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(max_iter=40, tol=1e-12),
    )

    def solve_with_weight(w):
        poses0, ei, ej, meas, fixed, si = with_priors(
            g.poses0, g.edge_i, g.edge_j, g.meas,
            prior_idx=[5], prior_poses=[target],
            prior_sqrt_info=[np.eye(6) * w],
            fixed=np.eye(1, n, 0, dtype=bool)[0])  # pose 0 fixed
        res = solve_pgo(poses0, ei, ej, meas, option,
                        sqrt_info=si, fixed=fixed)
        return float(np.linalg.norm(np.asarray(res.poses)[5, 3:]
                                    - target[3:]))

    strong = solve_with_weight(1e4)
    weak = solve_with_weight(1e-4)
    # Strong prior: pose 5 lands essentially at the prior target.
    assert strong < 1e-3
    # Weak prior: the (noise-free, anchored) odometry wins; pose 5 stays
    # ~1m away from the conflicting prior.
    assert weak > 0.9


def test_prior_factors_compose_with_sharding():
    """Priors are ordinary edges, so they must shard: world-2 solve of a
    prior-augmented graph matches world-1 exactly (f64)."""
    import dataclasses as dc

    from megba_tpu.models.pgo import with_priors

    g = make_synthetic_pose_graph(num_poses=14, loop_closures=4, seed=6)
    target = g.poses_gt[2]
    poses0, ei, ej, meas, fixed, si = with_priors(
        g.poses0, g.edge_i, g.edge_j, g.meas,
        prior_idx=[2], prior_poses=[target],
        prior_sqrt_info=[np.eye(6) * 10.0])
    base = ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=8),
        solver_option=SolverOption(max_iter=30, tol=1e-12),
    )
    res1 = solve_pgo(poses0, ei, ej, meas, base,
                     sqrt_info=si, fixed=fixed)
    res2 = solve_pgo(poses0, ei, ej, meas,
                     dc.replace(base, world_size=2),
                     sqrt_info=si, fixed=fixed)
    np.testing.assert_allclose(float(res2.cost), float(res1.cost),
                               rtol=1e-10, atol=1e-18)
    assert int(res2.iterations) == int(res1.iterations)
    np.testing.assert_allclose(np.asarray(res2.poses),
                               np.asarray(res1.poses), atol=1e-9)


def test_with_priors_edge_cases():
    """Degenerate inputs: no priors (identity transform + default
    gauge), prior on an already-fixed pose (harmless), bad indices and
    bad weight shapes raise up front with clear messages."""
    import pytest

    from megba_tpu.models.pgo import with_priors

    g = make_synthetic_pose_graph(num_poses=6, loop_closures=2, seed=1)
    n = g.poses0.shape[0]

    # p = 0: passthrough with the default gauge anchor.
    poses0, ei, ej, meas, fixed, si = with_priors(
        g.poses0, g.edge_i, g.edge_j, g.meas,
        prior_idx=np.zeros(0, np.int32), prior_poses=np.zeros((0, 6)))
    assert poses0.shape[0] == n and fixed[0] and fixed.sum() == 1
    assert si is None and ei.shape == g.edge_i.shape

    # Prior on a pose the caller also fixed: both constraints coexist
    # (the fixed pose just never moves; the prior edge costs a constant).
    caller_fixed = np.zeros(n, bool)
    caller_fixed[2] = True
    poses0, ei, ej, meas, fixed, si = with_priors(
        g.poses0, g.edge_i, g.edge_j, g.meas,
        prior_idx=[2], prior_poses=[g.poses_gt[2]], fixed=caller_fixed)
    assert fixed[2] and fixed[n] and fixed.sum() == 2

    with pytest.raises(ValueError, match="prior_idx out of range"):
        with_priors(g.poses0, g.edge_i, g.edge_j, g.meas,
                    prior_idx=[n], prior_poses=[np.zeros(6)])
    with pytest.raises(ValueError, match="prior_poses must be"):
        with_priors(g.poses0, g.edge_i, g.edge_j, g.meas,
                    prior_idx=[0], prior_poses=[np.zeros(5)])
    with pytest.raises(ValueError, match="prior_sqrt_info must be"):
        with_priors(g.poses0, g.edge_i, g.edge_j, g.meas,
                    prior_idx=[0], prior_poses=[np.zeros(6)],
                    prior_sqrt_info=np.broadcast_to(np.eye(6), (2, 6, 6)))
