"""Adversarial fixtures for the two layers that touch user bytes
(VERDICT r04 item 8): the g2o reader (io/g2o.py) and the BAL loaders
(io/bal.py + native/bal_parser.cpp).

Real exports hit these cases routinely: duplicate edges from merged
sessions, self-loop closures from buggy front-ends, disconnected
components from dropped tracking, Windows line endings, and files
truncated mid-transfer.  The reference has no ingestion layer beyond
its example-side fscanf loop (reference examples/BAL_Double.cpp:74-139),
so this coverage is ours to define: parse what is semantically valid,
reject what is not — loudly, with context, never with a crash or a
silently wrong graph.
"""

import io

import numpy as np
import pytest

from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
from megba_tpu.io.bal import load_bal, save_bal, loads_bal
from megba_tpu.io.g2o import read_g2o, solve_g2o
from megba_tpu.models.pgo import spanning_tree_init

_EDGE_INFO = "1 0 0 0 0 0 1 0 0 0 0 1 0 0 0 1 0 0 1 0 1"


def _opt(max_iter=10):
    # Tight stops: the self-loop test adds a constant cost floor that
    # would otherwise trip the relative-improvement stop early.
    return ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-14,
                               epsilon2=1e-16),
        solver_option=SolverOption(max_iter=40, tol=1e-12),
    )


# ---------------------------------------------------------------- g2o


def test_duplicate_edges_are_kept_as_repeated_constraints():
    """Two identical EDGE records = the same factor twice (merged
    sessions do this); both must survive parsing and the solve."""
    text = f"""\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 1.2 0 0 0 0 0 1
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 {_EDGE_INFO}
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 {_EDGE_INFO}
"""
    g = read_g2o(io.StringIO(text))
    assert g.edge_i.shape[0] == 2
    _, res = solve_g2o(g, _opt())
    assert float(res.cost) < 1e-6
    # The doubled factor doubles the initial cost vs the single-edge
    # graph — evidence the second record is not dropped.
    g1 = read_g2o(io.StringIO("\n".join(text.splitlines()[:-1]) + "\n"))
    _, res1 = solve_g2o(g1, _opt(max_iter=0))
    _, res2 = solve_g2o(g, _opt(max_iter=0))
    np.testing.assert_allclose(
        float(res2.initial_cost), 2 * float(res1.initial_cost), rtol=1e-12)


def test_self_loop_edge_contributes_constant_cost_only():
    """EDGE i i m: the relative pose of a vertex to itself is the
    identity regardless of the estimate, so the factor is a constant
    cost offset with zero gradient — it must not crash or corrupt the
    solve."""
    text = f"""\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 1.3 0 0 0 0 0 1
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 {_EDGE_INFO}
EDGE_SE3:QUAT 1 1 0.5 0 0 0 0 0 1 {_EDGE_INFO}
"""
    g = read_g2o(io.StringIO(text))
    _, res = solve_g2o(g, _opt())
    # The real edge is solved to zero; the self-loop's 0.5^2/... cost
    # floor remains (0.5**2 * 1.0 factor, halved by the 1/2 convention
    # if any — just assert the floor is the self-loop residual norm).
    assert np.isfinite(float(res.cost))
    assert float(res.cost) == pytest.approx(0.25, rel=1e-4)
    # And the movable vertex still reached its measurement.
    np.testing.assert_allclose(res.poses[1, 3], 1.0, atol=1e-4)


def test_spanning_tree_init_on_forest_keeps_unreachable_estimates():
    """Disconnected components: the BFS init must initialize the
    anchored component from measurements and leave unreachable poses
    at their file estimates (not zeros, not garbage)."""
    poses0 = np.array([
        [0, 0, 0, 0, 0, 0],
        [0, 0, 0, 9, 9, 9],     # reachable: bad file estimate
        [0, 0, 0, 5, 5, 5],     # island A
        [0, 0, 0, 6, 6, 6],     # island B, connected to A
    ], np.float64)
    edge_i = np.array([0, 2], np.int32)
    edge_j = np.array([1, 3], np.int32)
    meas = np.array([[0, 0, 0, 1, 0, 0],
                     [0, 0, 0, 0, 2, 0]], np.float64)
    fixed = np.array([True, False, False, False])
    out = spanning_tree_init(poses0, edge_i, edge_j, meas, fixed)
    # Component of the anchor: composed measurement wins.
    np.testing.assert_allclose(out[1], [0, 0, 0, 1, 0, 0], atol=1e-12)
    # Island: no path from an anchor -> file estimates preserved.
    np.testing.assert_allclose(out[2], poses0[2])
    np.testing.assert_allclose(out[3], poses0[3])


def test_crlf_g2o_parses_identically(tmp_path):
    text = f"""\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 1 0 0 0 0 0 1
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 {_EDGE_INFO}
FIX 0
"""
    lf = tmp_path / "lf.g2o"
    crlf = tmp_path / "crlf.g2o"
    lf.write_text(text)
    crlf.write_bytes(text.replace("\n", "\r\n").encode())
    a = read_g2o(str(lf))
    b = read_g2o(str(crlf))
    np.testing.assert_array_equal(a.ids, b.ids)
    np.testing.assert_allclose(a.poses, b.poses)
    np.testing.assert_allclose(a.meas, b.meas)
    np.testing.assert_allclose(a.info, b.info)
    assert a.had_fix == b.had_fix


# ---------------------------------------------------------------- BAL


def _tiny_bal_text():
    return loads_bal(
        "2 2 3\n"
        "0 0 1.0 2.0\n"
        "0 1 -1.5 0.25\n"
        "1 1 3.0 -2.0\n"
        + "\n".join(f"{0.01 * i:.17g}" for i in range(2 * 9 + 2 * 3)) + "\n"
    )


def test_crlf_bal_parses_identically(tmp_path):
    bal = _tiny_bal_text()
    lf = tmp_path / "lf.txt"
    crlf = tmp_path / "crlf.txt"
    save_bal(lf, bal)
    crlf.write_bytes(lf.read_bytes().replace(b"\n", b"\r\n"))
    a = load_bal(lf)
    b = load_bal(crlf)
    np.testing.assert_array_equal(a.cam_idx, b.cam_idx)
    np.testing.assert_allclose(a.cameras, b.cameras)
    np.testing.assert_allclose(a.points, b.points)
    np.testing.assert_allclose(a.obs, b.obs)


def test_truncated_bal_tail_raises_cleanly(tmp_path):
    """A file cut mid-transfer (every byte length) must raise ValueError
    — never crash the native scanner or hand back a partial problem.
    The NUL-terminated-buffer design claims exactly this safety."""
    bal = _tiny_bal_text()
    full = tmp_path / "full.txt"
    save_bal(full, bal)
    raw = full.read_bytes()
    cut = tmp_path / "cut.txt"
    # Chop at several points: inside the header, mid-observations,
    # mid-cameras.  (A cut inside the LAST token that leaves a valid
    # numeric prefix — e.g. "0.23" -> "0.2" — is undetectable in a
    # checksum-less text format; the reference's fscanf loader has the
    # same property, so the contract here is "any cut that removes a
    # whole token raises".)
    for frac in (0.02, 0.3, 0.7):
        cut.write_bytes(raw[: int(len(raw) * frac)])
        with pytest.raises(ValueError):
            load_bal(cut)
    # One byte past the final complete token boundary: drop the last
    # token entirely (cut at the preceding whitespace) -> must raise.
    last_ws = raw.rstrip().rfind(b"\n")
    cut.write_bytes(raw[:last_ws])
    with pytest.raises(ValueError):
        load_bal(cut)


def test_bal_trailing_garbage_raises(tmp_path):
    bal = _tiny_bal_text()
    p = tmp_path / "garbage.txt"
    save_bal(p, bal)
    with open(p, "a") as f:
        f.write("42.0 17.0\n")
    with pytest.raises(ValueError):
        load_bal(p)


def test_empty_and_whitespace_bal_raise(tmp_path):
    p = tmp_path / "empty.txt"
    p.write_text("")
    with pytest.raises(ValueError):
        load_bal(p)
    p.write_text(" \n \t \r\n ")
    with pytest.raises(ValueError):
        load_bal(p)


# ------------------------------------------- non-finite / duplicate input
#
# A single NaN in user bytes poisons every psum-reduced cost in the
# jitted solver; the robustness layer can CONTAIN that at runtime
# (RobustOption guards), but data that arrives broken must be refused at
# the ingestion boundary with file/line/index context, never solved.


def test_bal_nonfinite_observation_rejected_with_index(tmp_path):
    bal = _tiny_bal_text()
    bal.obs[1, 0] = np.nan
    p = tmp_path / "nan_obs.txt"
    save_bal(p, bal)
    with pytest.raises(ValueError) as exc:
        load_bal(p)
    msg = str(exc.value)
    assert "observation 1" in msg and "non-finite" in msg
    assert "cam 0" in msg and "pt 1" in msg  # actionable: names the edge
    assert "nan_obs.txt" in msg  # and the file


def test_bal_nonfinite_camera_and_point_rejected(tmp_path):
    bal = _tiny_bal_text()
    bal.cameras[1, 6] = np.inf
    p = tmp_path / "inf_cam.txt"
    save_bal(p, bal)
    with pytest.raises(ValueError, match="camera 1.*non-finite"):
        load_bal(p)
    bal = _tiny_bal_text()
    bal.points[0, 2] = -np.inf
    p2 = tmp_path / "inf_pt.txt"
    save_bal(p2, bal)
    with pytest.raises(ValueError, match="point 0.*non-finite"):
        load_bal(p2)


def test_bal_duplicate_edge_rejected_with_both_indices(tmp_path):
    text = (
        "2 2 3\n"
        "0 0 1.0 2.0\n"
        "1 1 3.0 -2.0\n"
        "0 0 1.5 2.5\n"  # same (cam, pt) as observation 0
        + "\n".join(f"{0.01 * i:.17g}" for i in range(2 * 9 + 2 * 3)) + "\n")
    with pytest.raises(ValueError) as exc:
        loads_bal(text)
    msg = str(exc.value)
    assert "duplicate" in msg and "cam 0" in msg and "pt 0" in msg
    assert "[0, 2]" in msg  # BOTH offending observation indices named
    p = tmp_path / "dup.txt"
    p.write_text(text)
    with pytest.raises(ValueError, match="duplicate"):
        load_bal(p)  # the native-parser path enforces the same contract


def test_g2o_nonfinite_vertex_rejected_with_line():
    text = """\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 nan 0 0 0 0 0 1
"""
    with pytest.raises(ValueError) as exc:
        read_g2o(io.StringIO(text))
    msg = str(exc.value)
    assert "line 2" in msg and "VERTEX 1" in msg and "non-finite" in msg


def test_g2o_nonfinite_edge_rejected_with_line():
    bad_info = _EDGE_INFO.replace("1 0 0 0 0 0 1", "inf 0 0 0 0 0 1", 1)
    text = f"""\
VERTEX_SE3:QUAT 0 0 0 0 0 0 0 1
VERTEX_SE3:QUAT 1 1 0 0 0 0 0 1
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 {_EDGE_INFO}
EDGE_SE3:QUAT 0 1 1 0 0 0 0 0 1 {bad_info}
"""
    with pytest.raises(ValueError) as exc:
        read_g2o(io.StringIO(text))
    msg = str(exc.value)
    assert "line 4" in msg and "EDGE 0 -> 1" in msg and "non-finite" in msg


def test_g2o_se2_nonfinite_measurement_rejected():
    text = """\
VERTEX_SE2 0 0 0 0
VERTEX_SE2 1 1 0 0
EDGE_SE2 0 1 nan 0 0 1 0 0 1 0 1
"""
    with pytest.raises(ValueError, match="line 3.*non-finite"):
        read_g2o(io.StringIO(text))
