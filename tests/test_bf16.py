"""bf16 MXU pipeline (ISSUE 15): option gating, precision policy,
collective payload casts, escalation/serving composition, and the
slow-lane solve parity + guard-cleanliness contracts.

Tier-1 tests here are compile-free (option validation, policy casts on
eager scalars, fingerprint splits, the escalation rung transform);
everything that lowers or solves a program is slow-marked (tier-1
budget — see ROADMAP).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from megba_tpu.common import (
    AlgoOption,
    PrecondKind,
    ProblemOption,
    RobustOption,
    SolverOption,
    validate_options,
)

BF16 = SolverOption(bf16=True)


def _opt(**kw):
    so = kw.pop("solver_option", BF16)
    kw.setdefault("dtype", np.float32)
    return ProblemOption(solver_option=so, **kw)


# ---------------------------------------------------------------------------
# Option gating (compile-free)
# ---------------------------------------------------------------------------

def test_bf16_refuses_f64_typed():
    with pytest.raises(ValueError, match="float64 problem asking for bf16"):
        validate_options(_opt(dtype=np.float64))


def test_bf16_collectives_requires_bf16():
    with pytest.raises(ValueError, match="requires SolverOption.bf16=True"):
        validate_options(_opt(
            solver_option=SolverOption(bf16_collectives=True)))


def test_bf16_refuses_mixed_precision_combo():
    with pytest.raises(ValueError, match="different rungs"):
        validate_options(_opt(mixed_precision_pcg=True))


def test_bf16_refuses_plain_solver():
    with pytest.raises(ValueError, match="only implemented for the Schur"):
        validate_options(_opt(use_schur=False))


def test_bf16_valid_configs_pass():
    validate_options(_opt())
    validate_options(_opt(solver_option=SolverOption(
        bf16=True, bf16_collectives=True), world_size=2))
    # composes with the 2-D mesh and every precond family's knobs
    validate_options(_opt(world_size=4, solver_option=SolverOption(
        bf16=True, bf16_collectives=True, mesh_2d=True, cam_blocks=2)))
    validate_options(_opt(solver_option=SolverOption(
        bf16=True, precond=PrecondKind.NEUMANN, neumann_order=1)))


def test_bf16_refuses_tiled_lowering_typed():
    # flat_solve refuses BEFORE any lowering — the tiled kernels have
    # no bf16 operand path and silently measuring f32 kernels under a
    # bf16 flag is exactly the silent-upcast failure mode.
    from megba_tpu.solve import flat_solve

    with pytest.raises(ValueError, match="bf16 does not compose"):
        flat_solve(lambda *a: None, np.zeros((2, 9), np.float32),
                   np.zeros((2, 3), np.float32), np.zeros((4, 2), np.float32),
                   np.zeros(4, np.int32), np.zeros(4, np.int32),
                   _opt(), use_tiled=True)


# ---------------------------------------------------------------------------
# Fingerprints / cache keys split for free (compile-free)
# ---------------------------------------------------------------------------

def test_bf16_joins_the_option_fingerprint():
    from megba_tpu.analysis.retrace import static_key

    base = _opt(solver_option=SolverOption())
    on = _opt()
    both = _opt(solver_option=SolverOption(bf16=True,
                                           bf16_collectives=True))
    keys = {static_key(o) for o in (base, on, both)}
    assert len(keys) == 3  # fleet bucket / artifact keys split for free


def test_bf16_rides_structured_option_config():
    from megba_tpu.observability.report import config_to_dict

    cfg = config_to_dict(_opt(solver_option=SolverOption(
        bf16=True, bf16_collectives=True)))
    assert cfg["solver_option"]["bf16"] is True
    assert cfg["solver_option"]["bf16_collectives"] is True


# ---------------------------------------------------------------------------
# Precision policy + payload casts (eager scalars, compile-free scale)
# ---------------------------------------------------------------------------

def test_edge_precision_modes():
    from megba_tpu.solver.pcg import _edge_precision, _ident

    up, vec, acc = _edge_precision(False, False)
    assert up is _ident and vec is _ident and acc is _ident
    up, vec, acc = _edge_precision(True, False)  # mixed: upcast rows
    assert up(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32
    assert vec is _ident and acc is _ident
    up, vec, acc = _edge_precision(False, True)  # bf16 pipeline
    assert up is _ident
    assert vec(jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16
    assert acc(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32


def test_collective_payload_cast_identity_when_off():
    from megba_tpu.parallel.mesh import collective_payload_cast

    down, up = collective_payload_cast(False)
    x = jnp.ones((3,), jnp.float32)
    assert down(x) is x and up(x) is x  # NO ops emitted: byte-identity
    down, up = collective_payload_cast(True)
    assert down(x).dtype == jnp.bfloat16
    assert up(down(x)).dtype == jnp.float32


def test_bf16_block_apply_accumulates_f32():
    from megba_tpu.solver.precond import (
        cam_block_matvec,
        cam_block_matvec_bf16,
    )

    rng = np.random.default_rng(0)
    H = jnp.asarray(rng.standard_normal((5, 4, 4)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((4, 5)), jnp.float32)
    y32 = cam_block_matvec(H, x)
    yb = cam_block_matvec_bf16(H.astype(jnp.bfloat16), x)
    assert yb.dtype == jnp.float32
    rel = float(jnp.linalg.norm(yb - y32) / jnp.linalg.norm(y32))
    assert rel < 3e-2  # bf16-operand accuracy, not garbage


# ---------------------------------------------------------------------------
# Serving / escalation composition (compile-free)
# ---------------------------------------------------------------------------

def test_escalation_rung2_strips_bf16():
    from megba_tpu.serving.resilience import EscalationPolicy

    pol = EscalationPolicy()
    base = _opt(solver_option=SolverOption(bf16=True,
                                           bf16_collectives=True),
                robust_option=RobustOption())
    r1 = pol.option_for_rung(base, 1)
    assert r1.solver_option.bf16  # guards-only rung keeps the pipeline
    r2 = pol.option_for_rung(base, 2)
    assert not r2.solver_option.bf16
    assert not r2.solver_option.bf16_collectives


# ---------------------------------------------------------------------------
# Slow lane: the pipeline actually solves, guard-clean, at parity
# ---------------------------------------------------------------------------

def _scene():
    from megba_tpu.io.synthetic import make_synthetic_bal

    return make_synthetic_bal(
        num_cameras=8, num_points=60, obs_per_point=3, seed=0,
        param_noise=4e-2, pixel_noise=0.3, dtype=np.float32)


def _solve(s, **kw):
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    world = kw.pop("world", 1)
    mesh2d = kw.pop("mesh2d", False)
    forcing = kw.pop("forcing", True)
    lm = kw.pop("lm", 8)
    so = SolverOption(max_iter=kw.pop("max_iter", 100), forcing=forcing,
                      warm_start=forcing,
                      mesh_2d=mesh2d, cam_blocks=2 if mesh2d else 0, **kw)
    opt = ProblemOption(dtype=np.float32, world_size=world,
                        algo_option=AlgoOption(max_iter=lm),
                        solver_option=so,
                        robust_option=RobustOption(guards=True))
    f = make_residual_jacobian_fn()
    return flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                      s.pt_idx, opt, use_tiled=False)


@pytest.mark.slow
def test_bf16_solve_parity_and_guard_clean():
    """The bf16 pipeline converges within the documented band of the
    f32 control with ZERO guard/recovery events — the acceptance
    contract, on the small scene (the venice-10% certification lives
    in run_tests.sh / BENCH_bf16.json)."""
    s = _scene()
    r32 = _solve(s)
    rbf = _solve(s, bf16=True)
    gap = abs(float(rbf.cost) - float(r32.cost)) / float(r32.cost)
    assert gap <= 2e-2, gap
    assert int(rbf.recoveries) == 0
    it = int(rbf.iterations)
    assert int(np.asarray(rbf.trace.pcg_breakdown[:it]).sum()) == 0


@pytest.mark.slow
def test_bf16_collectives_world2_parity():
    s = _scene()
    r32 = _solve(s, world=2)
    rbf = _solve(s, world=2, bf16=True, bf16_collectives=True)
    gap = abs(float(rbf.cost) - float(r32.cost)) / float(r32.cost)
    assert gap <= 2e-2, gap
    assert int(rbf.recoveries) == 0


@pytest.mark.slow
def test_bf16_composes_with_2d_mesh():
    # Run to convergence (20 LM iters): the heavily-noised toy's
    # MID-trajectory costs wobble several % between summation
    # groupings (the 2-D bf16 operator regroups sums on top of the
    # rounding), while the converged basins agree at bf16-operator
    # accuracy — measured 1.6e-3 here; venice-10% certifies 8.9e-8
    # (BENCH_bf16.json).
    s = _scene()
    rbf = _solve(s, world=4, mesh2d=True, bf16=True, bf16_collectives=True,
                 lm=20)
    r32 = _solve(s, lm=20)
    gap = abs(float(rbf.cost) - float(r32.cost)) / float(r32.cost)
    assert gap <= 3e-2, gap
    assert int(rbf.recoveries) == 0


@pytest.mark.slow
def test_bf16_stagnation_exits_clean_not_broken():
    """Driving the bf16 inner solve far below its attainable floor
    (absolute tol 1e-8, refuse disabled) must STOP at the noise floor
    via the stagnation exit — best iterate restored, zero recoveries —
    instead of restart-thrashing into FATAL/RECOVERED."""
    from megba_tpu.common import SolveStatus

    s = _scene()
    r = _solve(s, bf16=True, forcing=False, tol=1e-8,
               refuse_ratio=1e30, max_iter=40)
    assert int(r.recoveries) == 0
    assert int(r.status) in (SolveStatus.MAX_ITER, SolveStatus.CONVERGED)
    assert np.isfinite(float(r.cost))
