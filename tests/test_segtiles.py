"""Block-aligned tiled segment reduction: plan invariants + kernel parity.

The kernels are the replacement for the per-edge scatter/gather of the
reference's assembly/SpMV path (build_linear_system.cu:88-146,
implicit_schur_pcg_solver.cu:20-90); here they are verified in Pallas
interpret mode against plain numpy scatter/gather ground truth.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from megba_tpu.ops.segtiles import (
    TilePlan,
    build_tile_plan,
    cross_perm,
    device_plan,
    expand_fallback,
    reduce_fallback,
    tile_expand,
    tile_reduce,
)


def _check_plan_invariants(plan: TilePlan, idx: np.ndarray, num_segments):
    n_tiles = plan.n_tiles
    assert plan.n_slots == n_tiles * plan.tile
    # Every real edge appears exactly once.
    real = plan.mask > 0
    assert real.sum() == idx.shape[0]
    assert np.array_equal(np.sort(plan.perm[real]), np.arange(idx.shape[0]))
    # Slots carry the right segment ids.
    assert np.array_equal(plan.seg[real], idx[plan.perm[real]])
    # Each tile touches exactly one block, non-decreasing, all blocks
    # visited, first-flags correct.
    seg_by_tile = plan.seg.reshape(n_tiles, plan.tile)
    blk_by_tile = seg_by_tile // plan.block
    assert np.all(blk_by_tile == blk_by_tile[:, :1])
    tb = plan.tile_block
    assert np.array_equal(blk_by_tile[:, 0], tb)
    assert np.all(np.diff(tb) >= 0)
    assert set(tb.tolist()) == set(range(plan.num_blocks))
    first = np.ones_like(tb)
    first[1:] = tb[1:] != tb[:-1]
    assert np.array_equal(plan.tile_first, first)
    # local in range
    assert plan.local.min() >= 0 and plan.local.max() < plan.block
    # Padding fill (running-max per block) keeps the whole slot stream
    # non-decreasing: the `indices_are_sorted=True` scatter promise.
    assert np.all(np.diff(plan.seg.astype(np.int64)) >= 0)


@pytest.mark.parametrize("seed,n,ns,tile,block", [
    (0, 1000, 37, 64, 16),
    (1, 5000, 501, 128, 64),
    (2, 300, 900, 64, 128),   # more segments than edges (empty blocks)
    (3, 257, 1, 64, 8),       # single segment
])
def test_plan_invariants(seed, n, ns, tile, block):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, ns, n).astype(np.int32)
    plan = build_tile_plan(idx, ns, tile, block)
    _check_plan_invariants(plan, idx, ns)


def test_plan_sorted_input_low_padding():
    # Pre-sorted camera-style input with many edges per segment: padding
    # stays under one tile per block.
    rng = np.random.default_rng(7)
    idx = np.sort(rng.integers(0, 16, 10_000)).astype(np.int32)
    plan = build_tile_plan(idx, 16, 128, 8)
    _check_plan_invariants(plan, idx, 16)
    assert plan.n_slots - plan.n_edges <= plan.num_blocks * 128


@pytest.mark.parametrize("F", [3, 12])
@pytest.mark.parametrize("tile,block", [(128, 8), (256, 128)])
def test_tile_reduce_matches_numpy(F, tile, block):
    rng = np.random.default_rng(42)
    n, ns = 3000, 61
    idx = rng.integers(0, ns, n).astype(np.int32)
    data = rng.standard_normal((F, n)).astype(np.float32)

    plan = build_tile_plan(idx, ns, tile, block)
    dp = device_plan(plan)
    slot_data = (data[:, plan.perm] * plan.mask).astype(np.float32)

    ref = np.zeros((F, ns), np.float64)
    for f in range(F):
        np.add.at(ref[f], idx, data[f].astype(np.float64))

    got = np.asarray(
        tile_reduce(jnp.asarray(slot_data), dp, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    fb = np.asarray(reduce_fallback(jnp.asarray(slot_data), dp))
    np.testing.assert_allclose(fb, ref, rtol=2e-5, atol=2e-5)


def test_tile_expand_matches_take():
    rng = np.random.default_rng(3)
    n, ns, F = 2000, 97, 9
    idx = rng.integers(0, ns, n).astype(np.int32)
    table = rng.standard_normal((F, ns)).astype(np.float32)
    plan = build_tile_plan(idx, ns, 128, 32)
    dp = device_plan(plan)

    got = np.asarray(tile_expand(jnp.asarray(table), dp, interpret=True))
    real = plan.mask > 0
    expect = table[:, idx[plan.perm[real]]]
    np.testing.assert_array_equal(got[:, real], expect)

    fb = np.asarray(expand_fallback(jnp.asarray(table), dp))
    np.testing.assert_array_equal(fb[:, real], expect)


def test_cross_perm_roundtrip():
    # Two plans over the same edges (camera-sorted and point-sorted
    # orders); cross_perm moves per-edge rows between slot orders.
    rng = np.random.default_rng(11)
    n = 4000
    cam = np.sort(rng.integers(0, 40, n)).astype(np.int32)
    pt = rng.integers(0, 700, n).astype(np.int32)
    plan_c = build_tile_plan(cam, 40, 128, 16)
    plan_p = build_tile_plan(pt, 700, 64, 128)

    x_edges = rng.standard_normal((2, n)).astype(np.float32)
    x_c = x_edges[:, plan_c.perm] * plan_c.mask
    x_p = x_edges[:, plan_p.perm] * plan_p.mask

    inv_c2p = cross_perm(plan_p, plan_c)  # for each pt-slot: cam slot
    moved = x_c[:, inv_c2p] * plan_p.mask
    np.testing.assert_array_equal(moved, x_p)

    inv_p2c = cross_perm(plan_c, plan_p)
    back = x_p[:, inv_p2c] * plan_c.mask
    np.testing.assert_array_equal(back, x_c)


@pytest.mark.parametrize("interp", [False, True])
def test_coupling_expand_reduce(interp):
    # Fused (gather + J.x) and (J^T.u + reduce) vs the composition of
    # their unfused parts.
    rng = np.random.default_rng(9)
    n, ns, d, od = 3000, 83, 9, 2
    idx = rng.integers(0, ns, n).astype(np.int32)
    plan = build_tile_plan(idx, ns, 256, 32)
    dp = device_plan(plan)
    nslots = plan.n_slots
    J = rng.standard_normal((od * d, nslots)).astype(np.float32)
    J *= plan.mask
    table = rng.standard_normal((d, ns)).astype(np.float32)
    u_in = rng.standard_normal((od, nslots)).astype(np.float32)

    from megba_tpu.ops.segtiles import coupling_expand, coupling_reduce

    got_u = np.asarray(coupling_expand(
        jnp.asarray(table), jnp.asarray(J), dp, d,
        use_kernels=False, interpret=interp))
    pe = table[:, plan.seg]
    ref_u = np.stack([
        sum(J[o * d + a] * pe[a] for a in range(d)) for o in range(od)])
    np.testing.assert_allclose(got_u, ref_u, rtol=2e-5, atol=2e-5)

    got_r = np.asarray(coupling_reduce(
        jnp.asarray(J), jnp.asarray(u_in), dp, d,
        use_kernels=False, interpret=interp))
    te = np.stack([
        sum(J[o * d + b] * u_in[o] for o in range(od)) for b in range(d)])
    ref_r = np.zeros((d, ns), np.float64)
    for b in range(d):
        np.add.at(ref_r[b], plan.seg, te[b].astype(np.float64))
    np.testing.assert_allclose(got_r, ref_r, rtol=2e-4, atol=2e-4)


def test_reduce_accumulation_many_tiles_per_block():
    # Forces the in-kernel accumulate branch (several tiles per block).
    rng = np.random.default_rng(5)
    n, ns = 4096, 4
    idx = rng.integers(0, ns, n).astype(np.int32)
    data = rng.standard_normal((5, n)).astype(np.float32)
    plan = build_tile_plan(idx, ns, 128, 8)
    assert plan.n_tiles > plan.num_blocks
    dp = device_plan(plan)
    slot_data = (data[:, plan.perm] * plan.mask).astype(np.float32)
    ref = np.zeros((5, ns), np.float64)
    for f in range(5):
        np.add.at(ref[f], idx, data[f].astype(np.float64))
    got = np.asarray(
        tile_reduce(jnp.asarray(slot_data), dp, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
