"""Plain (non-Schur) full-system PCG — the path the reference left TODO."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.algo import lm_solve
from megba_tpu.common import (
    AlgoOption,
    ComputeKind,
    JacobianMode,
    LinearSystemKind,
    ProblemOption,
    SolverOption,
    validate_options,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solver import dense_reference_solve, plain_pcg_solve
from tests.test_solver import build_test_system


@pytest.mark.parametrize("compute_kind", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_plain_pcg_matches_dense(compute_kind):
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system(compute_kind=compute_kind)
    region = jnp.asarray(100.0)
    dx_cam_d, dx_pt_d = dense_reference_solve(system, Jc, Jp, cam_idx, pt_idx, region)
    out = plain_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, region,
                          max_iter=2000, tol=1e-14, tol_relative=True,
                          refuse_ratio=1e30, compute_kind=compute_kind)
    np.testing.assert_allclose(out.dx_cam, dx_cam_d, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(out.dx_pt, dx_pt_d, rtol=1e-5, atol=1e-7)


def test_plain_lm_converges_and_matches_schur():
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=0, param_noise=4e-2, pixel_noise=0.3)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    args = (jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(s.obs.T),
            jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)))

    def opt(use_schur):
        return ProblemOption(
            use_schur=use_schur,
            algo_option=AlgoOption(max_iter=25, epsilon1=1e-9, epsilon2=1e-12),
            solver_option=SolverOption(max_iter=800, tol=1e-12,
                                       tol_relative=True, refuse_ratio=1e30))

    schur = lm_solve(f, *args, opt(True))
    plain = lm_solve(f, *args, opt(False))
    # Both solve the same damped normal equations; final costs agree.
    np.testing.assert_allclose(float(plain.cost), float(schur.cost), rtol=1e-6)
    assert int(plain.accepted) > 0


def test_plain_mode_option_validation():
    # use_schur=False no longer raises, and tolerates BASE linear system.
    o = ProblemOption(use_schur=False,
                      linear_system_kind=LinearSystemKind.BASE_LINEAR_SYSTEM)
    validate_options(o)
    with pytest.raises(ValueError, match="use_schur=True requires"):
        validate_options(ProblemOption(
            use_schur=True,
            linear_system_kind=LinearSystemKind.BASE_LINEAR_SYSTEM))


def test_plain_rejects_mixed_precision():
    # Upfront at option validation...
    with pytest.raises(ValueError, match="mixed_precision_pcg"):
        validate_options(ProblemOption(use_schur=False, mixed_precision_pcg=True))
    # ...and at the solver for direct callers.
    system, r, Jc, Jp, cam_idx, pt_idx = build_test_system()
    with pytest.raises(NotImplementedError, match="mixed_precision"):
        plain_pcg_solve(system, Jc, Jp, cam_idx, pt_idx, jnp.asarray(10.0),
                        mixed_precision=True)
