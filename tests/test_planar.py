"""Planar (2D BA) model family: the solver stack is dimension-generic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import cpu_devices

from megba_tpu.algo import lm_solve
from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.models import planar
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.parallel import distributed_lm_solve, make_mesh, shard_edge_arrays


def make_option(max_iter=20):
    return ProblemOption(
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-10, epsilon2=1e-13),
        solver_option=SolverOption(max_iter=150, tol=1e-14, refuse_ratio=1e30))


def test_planar_residual_shapes_and_fd():
    s = planar.make_synthetic_planar(seed=1)
    cam = jnp.asarray(s.cameras_gt[0])
    pt = jnp.asarray(s.points_gt[0])
    obs = jnp.asarray([1.5])
    r = planar.residual(cam, pt, obs)
    assert r.shape == (1,)
    Jc, Jp = jax.jacfwd(planar.residual, argnums=(0, 1))(cam, pt, obs)
    assert Jc.shape == (1, 4) and Jp.shape == (1, 2)
    eps = 1e-6
    for i in range(4):
        d = np.zeros(4); d[i] = eps
        fd = (np.asarray(planar.residual(cam + d, pt, obs))
              - np.asarray(planar.residual(cam - d, pt, obs))) / (2 * eps)
        np.testing.assert_allclose(Jc[:, i], fd, rtol=1e-5, atol=1e-6)


def test_planar_lm_converges_noiseless():
    s = planar.make_synthetic_planar(num_cameras=6, num_points=50,
                                     obs_per_point=4, noise=0.0,
                                     param_noise=2e-2, seed=0)
    f = make_residual_jacobian_fn(residual_fn=planar.residual,
                                  mode=JacobianMode.AUTODIFF)
    res = lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(s.obs.T),
        jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)),
        make_option())
    assert float(res.initial_cost) > 1.0
    assert float(res.cost) < 1e-9 * float(res.initial_cost)


def test_planar_distributed():
    s = planar.make_synthetic_planar(num_cameras=6, num_points=50,
                                     obs_per_point=4, noise=0.1, seed=2)
    f = make_residual_jacobian_fn(residual_fn=planar.residual,
                                  mode=JacobianMode.AUTODIFF)
    obs, cam_idx, pt_idx, mask = shard_edge_arrays(s.obs, s.cam_idx, s.pt_idx, 4)
    res = distributed_lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(obs.T),
        jnp.asarray(cam_idx), jnp.asarray(pt_idx), jnp.asarray(mask),
        make_option(12), make_mesh(4, cpu_devices(4)))
    single = lm_solve(
        f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(s.obs.T),
        jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)),
        make_option(12))
    np.testing.assert_allclose(float(res.cost), float(single.cost), rtol=1e-8)


def test_planar_through_problem_api():
    # Custom-dimension vertices + custom forward through the g2o facade.
    from megba_tpu import BaseEdge, BaseProblem, CameraVertex, PointVertex

    class PlanarEdge(BaseEdge):
        def forward(self):
            cam = self.vertex_estimation(0)
            pt = self.vertex_estimation(1)
            return planar.residual(cam, pt, self.get_measurement())

    s = planar.make_synthetic_planar(num_cameras=5, num_points=30,
                                     obs_per_point=3, noise=0.05, seed=3)
    pb = BaseProblem(make_option(15))
    cams = [CameraVertex(c) for c in s.cameras0]
    pts = [PointVertex(p) for p in s.points0]
    for i, v in enumerate(cams):
        pb.append_vertex(i, v)
    for j, v in enumerate(pts):
        pb.append_vertex(1000 + j, v)
    for c, p, uv in zip(s.cam_idx, s.pt_idx, s.obs):
        pb.append_edge(PlanarEdge([cams[c], pts[p]], measurement=uv))
    res = pb.solve()
    assert float(res.cost) < float(res.initial_cost) * 1e-3
    assert cams[0].estimation.shape == (4,)
