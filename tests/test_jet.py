"""Jet (dual-number) op tests vs jax.jvp (SURVEY.md §4a)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.ops.jet import Jet, seed_jets


def jvp_grad(f, xs):
    """Full Jacobian rows of elementwise f via jax.jvp, for comparison."""
    n = len(xs)
    outs = []
    for i in range(n):
        tangents = [jnp.ones_like(x) if j == i else jnp.zeros_like(x)
                    for j, x in enumerate(xs)]
        _, g = jax.jvp(f, (xs,), (tangents,))
        outs.append(g)
    return jnp.stack(outs)


@pytest.mark.parametrize("op", ["add", "sub", "mul", "div"])
def test_binary_ops_match_jvp(op):
    r = np.random.default_rng(0)
    a = jnp.asarray(r.normal(size=32) + 3.0)
    b = jnp.asarray(r.normal(size=32) + 3.0)
    ja, jb = seed_jets([a, b])

    def f(xs):
        x, y = xs
        return {"add": x + y, "sub": x - y, "mul": x * y, "div": x / y}[op]

    got = {"add": ja + jb, "sub": ja - jb, "mul": ja * jb, "div": ja / jb}[op]
    np.testing.assert_allclose(got.value, f([a, b]), rtol=1e-12)
    np.testing.assert_allclose(got.grad, jvp_grad(f, [a, b]), rtol=1e-12)


def test_scalar_both_orders():
    a = jnp.asarray([1.0, 2.0, 4.0])
    (j,) = seed_jets([a])
    np.testing.assert_allclose((2.0 - j).value, 2.0 - a)
    np.testing.assert_allclose((2.0 - j).grad[0], -np.ones(3))
    np.testing.assert_allclose((3.0 / j).value, 3.0 / a)
    np.testing.assert_allclose((3.0 / j).grad[0], -3.0 / a**2)
    np.testing.assert_allclose((j * 5.0).grad[0], 5.0 * np.ones(3))
    np.testing.assert_allclose((-j).grad[0], -np.ones(3))


@pytest.mark.parametrize("name", ["abs", "sqrt", "sin", "cos"])
def test_unary_ops_match_jvp(name):
    r = np.random.default_rng(1)
    a = jnp.asarray(np.abs(r.normal(size=16)) + 0.5)
    if name == "abs":
        a = a * jnp.asarray(r.choice([-1.0, 1.0], size=16))
    (j,) = seed_jets([a])
    got = getattr(j, name)()
    f = {"abs": jnp.abs, "sqrt": jnp.sqrt, "sin": jnp.sin, "cos": jnp.cos}[name]
    np.testing.assert_allclose(got.value, f(a), rtol=1e-12)
    np.testing.assert_allclose(got.grad, jvp_grad(lambda xs: f(xs[0]), [a]),
                               rtol=1e-12)


def test_composite_expression_matches_jacfwd():
    # A BAL-flavoured composite: f*(1 + k*n)*x / z built from Jet ops must
    # reproduce jacfwd column-for-column.
    r = np.random.default_rng(2)
    x, z, f, k = (jnp.asarray(r.normal(size=8) + 2.0) for _ in range(4))
    jx, jz, jf, jk = seed_jets([x, z, f, k])
    n = jx * jx
    expr = jf * (1.0 + jk * n) * jx / jz

    def ref(args):
        x, z, f, k = args
        return f * (1.0 + k * x * x) * x / z

    np.testing.assert_allclose(expr.value, ref([x, z, f, k]), rtol=1e-12)
    np.testing.assert_allclose(expr.grad, jvp_grad(ref, [x, z, f, k]), rtol=1e-12)


def test_jet_is_jit_and_vmap_compatible():
    a = jnp.arange(1.0, 9.0)

    @jax.jit
    def run(a):
        (j,) = seed_jets([a])
        return (j * j + 3.0).sqrt().value

    np.testing.assert_allclose(run(a), np.sqrt(a**2 + 3.0), rtol=1e-12)


def test_constant_has_zero_grad():
    c = Jet.constant(jnp.ones(4), n_grad=3)
    assert c.grad.shape == (3, 4)
    np.testing.assert_array_equal(c.grad, 0.0)
