"""Fleet-resilience tests: deadlines, escalation, admission, breaker.

Chaos-path coverage for serving/resilience.py + the FleetQueue surgery
(ISSUE 8): deadline shed before/after dispatch, the escalation ladder
rung by rung (a poisoned problem heals at rung >= 1 while clean
batch-mates stay bitwise identical to an unpoisoned run), breaker
trip / half-open / recovery, admission-control reject vs. block, and
deterministic backoff under a fixed seed.

Compile discipline (tier-1 is at ~80% of its budget): everything that
traces or compiles a solver program is marked `slow` and draws from the
SAME canonical OPT64 / problem set as tests/test_serving.py, so the jit
caches and the persistent compile cache amortise across the full lane.
The host-side state machines (policies, breaker, queue plumbing driven
by injected dispatch chaos that fails BEFORE any JAX work) run in
tier-1 compile-free.
"""

import dataclasses
import time

import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    PrecondKind,
    PreconditionerKind,
    ProblemOption,
    SolverOption,
    SolveStatus,
    status_retryable,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.robustness.faults import (
    DispatchChaos,
    FaultPlan,
    InjectedDispatchError,
    close_fault_window,
    inert_fault_plan,
    lower_fault_plan,
    make_nan_burst,
    stack_fault_plans,
)
from megba_tpu.serving import (
    BreakerPolicy,
    BreakerState,
    BucketTripped,
    CircuitBreaker,
    DeadlineExceeded,
    EscalationPolicy,
    FleetProblem,
    FleetQueue,
    FleetStats,
    QueueRejected,
    RejectPolicy,
    solve_many,
)

OPT64 = ProblemOption(dtype=np.float64,
                      algo_option=AlgoOption(max_iter=6),
                      solver_option=SolverOption(max_iter=12, tol=1e-10))


def _mk(seed, n_pt=24, n_cam=4):
    s = make_synthetic_bal(num_cameras=n_cam, num_points=n_pt,
                           obs_per_point=3, seed=seed, param_noise=2e-2,
                           pixel_noise=0.3, dtype=np.float64)
    return FleetProblem.from_synthetic(s, name=f"s{seed}_p{n_pt}")


def _poison(problem: FleetProblem, edges=(3, 17)) -> FleetProblem:
    """NaN burst on the PRE-LOOP linearisation (window [0, 1)): with
    guards off the carried cost is NaN from the start and every trial
    is rejected against it (STALLED + non-finite cost); with guards on
    the adoption path heals it (RECOVERED)."""
    plan = make_nan_burst(problem.obs.shape[0], list(edges), start=0,
                          stop=1, n_points=problem.points.shape[0],
                          dtype=np.float64)
    return dataclasses.replace(problem, fault_plan=plan,
                               name=problem.name + "_poisoned")


# ---------------------------------------------------------------------------
# EscalationPolicy: rung transforms, backoff, retry predicate
# ---------------------------------------------------------------------------

def test_escalation_rung_transforms_are_cumulative():
    pol = EscalationPolicy()
    base = ProblemOption(
        dtype=np.float32,
        solver_option=SolverOption(max_iter=30, forcing=True,
                                   warm_start=True,
                                   precond=PrecondKind.NEUMANN,
                                   preconditioner=(
                                       PreconditionerKind.SCHUR_DIAG)))
    r0 = pol.option_for_rung(base, 0)
    assert r0 == base  # rung 0 = as submitted
    r1 = pol.option_for_rung(base, 1)
    assert r1.robust_option.guards
    assert r1.solver_option == base.solver_option  # only guards changed
    r2 = pol.option_for_rung(base, 2)
    assert r2.robust_option.guards  # cumulative
    assert r2.solver_option.precond == PrecondKind.JACOBI
    assert r2.solver_option.preconditioner == PreconditionerKind.HPP
    assert not r2.solver_option.forcing and not r2.solver_option.warm_start
    assert r2.solver_option.max_iter == 60
    assert np.dtype(r2.dtype) == np.float32
    r3 = pol.option_for_rung(base, 3)
    assert np.dtype(r3.dtype) == np.float64  # the f64 re-solve rung
    assert r3.robust_option.guards
    with pytest.raises(ValueError):
        pol.option_for_rung(base, 4)
    # rung >= 1 inflates initial damping as an OPERAND
    assert pol.initial_region_for_rung(base, 0) is None
    assert pol.initial_region_for_rung(base, 1) == pytest.approx(
        base.algo_option.initial_region / pol.damping_deflation)


def test_escalation_backoff_deterministic_and_bounded():
    a = EscalationPolicy(seed=7, backoff_base_s=0.02, backoff_factor=2.0,
                         backoff_jitter=0.5)
    b = EscalationPolicy(seed=7, backoff_base_s=0.02, backoff_factor=2.0,
                         backoff_jitter=0.5)
    seq_a = [a.backoff_s(seq, k) for seq in range(4) for k in (1, 2, 3)]
    seq_b = [b.backoff_s(seq, k) for seq in range(4) for k in (1, 2, 3)]
    assert seq_a == seq_b  # fixed seed replays the exact schedule
    c = EscalationPolicy(seed=8)
    assert any(a.backoff_s(s, 1) != c.backoff_s(s, 1) for s in range(4))
    # jitter stays inside [1-j, 1+j] of the exponential base
    for seq in range(8):
        for attempt in (1, 2, 3):
            base = 0.02 * 2.0 ** (attempt - 1)
            got = a.backoff_s(seq, attempt)
            assert 0.5 * base <= got <= 1.5 * base
    # problems de-synchronise: not every problem gets the same jitter
    assert len({a.backoff_s(s, 1) for s in range(8)}) > 1
    # jitter-free policy is the plain exponential
    flat = EscalationPolicy(backoff_jitter=0.0, backoff_base_s=0.01)
    assert flat.backoff_s(3, 2) == pytest.approx(0.02)
    with pytest.raises(ValueError):
        a.backoff_s(0, 0)
    with pytest.raises(ValueError):
        EscalationPolicy(max_rungs=0)
    with pytest.raises(ValueError):
        EscalationPolicy(backoff_jitter=1.0)
    with pytest.raises(ValueError):
        EscalationPolicy(backoff_factor=0.5)


def test_retry_predicate_and_status_retryable():
    pol = EscalationPolicy()
    assert pol.should_retry(int(SolveStatus.STALLED))
    assert pol.should_retry(int(SolveStatus.FATAL_NONFINITE))
    assert not pol.should_retry(int(SolveStatus.CONVERGED), 1.0)
    assert not pol.should_retry(int(SolveStatus.MAX_ITER), 1.0)
    assert not pol.should_retry(int(SolveStatus.RECOVERED), 1.0)
    # NaN cost under a benign status is still unusable
    assert pol.should_retry(int(SolveStatus.MAX_ITER), float("nan"))
    assert pol.should_retry(99)  # unknown codes never deliver silently
    # the shared common.py predicate agrees
    assert status_retryable(int(SolveStatus.STALLED))
    assert status_retryable(int(SolveStatus.CONVERGED), float("inf"))
    assert not status_retryable(int(SolveStatus.CONVERGED), 1.0)


# ---------------------------------------------------------------------------
# CircuitBreaker state machine (pure host, injected clock)
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    events = []
    cb = CircuitBreaker(BreakerPolicy(trip_after=2, cooldown_s=1.0),
                        on_event=lambda e, b, r: events.append((e, b)))
    assert cb.state("b") is BreakerState.CLOSED
    cb.record_failure("b", "boom", now=0.0)
    assert cb.state("b") is BreakerState.CLOSED  # streak 1 < trip_after
    cb.check_submit("b", now=0.1)  # closed: no-op
    cb.record_failure("b", "boom2", now=0.2)
    assert cb.state("b") is BreakerState.OPEN
    with pytest.raises(BucketTripped, match="boom2"):
        cb.check_submit("b", now=0.5)
    assert not cb.admit("b", now=0.5)  # still cooling down
    assert cb.reopen_at("b") == pytest.approx(1.2)
    cb.check_submit("b", now=1.5)  # past cooldown: submits flow again
    assert cb.admit("b", now=1.5)  # half-open probe admitted
    assert cb.state("b") is BreakerState.HALF_OPEN
    assert not cb.admit("b", now=1.6)  # one probe at a time
    cb.record_failure("b", "probe died", now=1.7)
    assert cb.state("b") is BreakerState.OPEN  # failed probe re-opens
    assert cb.admit("b", now=3.0)
    cb.record_success("b")
    assert cb.state("b") is BreakerState.CLOSED
    assert cb.reopen_at("b") is None
    # a success resets the streak: two more failures needed to re-trip
    cb.record_failure("b", "x", now=3.1)
    assert cb.state("b") is BreakerState.CLOSED
    # independent buckets
    assert cb.state("other") is BreakerState.CLOSED
    assert [e for e, _ in events] == [
        "trip", "fast_fail", "probe", "trip", "probe", "recover"]
    with pytest.raises(ValueError):
        BreakerPolicy(trip_after=0)


# ---------------------------------------------------------------------------
# Queue plumbing under chaos (compile-free: failures fire pre-solve)
# ---------------------------------------------------------------------------

def test_deadline_shed_before_dispatch():
    stats = FleetStats()
    with FleetQueue(OPT64, max_batch=64, max_wait_s=30.0,
                    stats=stats) as q:
        fut = q.submit(_mk(0), deadline_s=0.0)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded, match="shed before dispatch"):
            fut.result(timeout=10)
        # shed at the deadline, not at the 30s batch flush
        assert time.monotonic() - t0 < 5.0
    assert stats.sheds == 1
    assert stats.problems == 0  # no device work was burned
    with pytest.raises(ValueError):
        q2 = FleetQueue(OPT64)
        try:
            q2.submit(_mk(0), deadline_s=-1.0)
        finally:
            q2.close()


def test_admission_control_reject_raise():
    stats = FleetStats()
    with FleetQueue(OPT64, max_batch=64, max_wait_s=30.0, stats=stats,
                    max_pending=2) as q:
        f1 = q.submit(_mk(1), deadline_s=0.2)
        f2 = q.submit(_mk(2), deadline_s=0.2)
        with pytest.raises(QueueRejected, match="max_pending=2"):
            q.submit(_mk(3), deadline_s=0.2)
        # capacity frees once the two shed; the queue serves again
        for f in (f1, f2):
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=10)
        f4 = q.submit(_mk(4), deadline_s=0.0)
        with pytest.raises(DeadlineExceeded):
            f4.result(timeout=10)
    assert stats.rejected == 1
    assert stats.sheds == 3
    assert stats.queue_depth_peak == 2
    with pytest.raises(ValueError):
        FleetQueue(OPT64, max_pending=0)


def test_admission_control_block_times_out():
    stats = FleetStats()
    with FleetQueue(OPT64, max_batch=64, max_wait_s=30.0, stats=stats,
                    max_pending=1, reject_policy=RejectPolicy.BLOCK,
                    block_timeout_s=0.15) as q:
        f1 = q.submit(_mk(1), deadline_s=30.0)
        t0 = time.monotonic()
        with pytest.raises(QueueRejected, match="for 0.15s"):
            q.submit(_mk(2))
        assert time.monotonic() - t0 >= 0.15
        # a cancel before dispatch frees the slot without device work
        assert f1.cancel()
        f3 = q.submit(_mk(3), deadline_s=0.0)
        with pytest.raises(DeadlineExceeded):
            f3.result(timeout=10)
    assert stats.rejected == 1
    assert stats.problems == 0


def test_breaker_trips_bucket_and_submits_fail_fast():
    stats = FleetStats()
    chaos = DispatchChaos(fail_first=99)  # every dispatch dies pre-solve
    with FleetQueue(OPT64, max_batch=1, max_wait_s=0.0, stats=stats,
                    chaos=chaos,
                    breaker=BreakerPolicy(trip_after=2,
                                          cooldown_s=60.0)) as q:
        f1, f2 = q.submit(_mk(1)), q.submit(_mk(2))
        for f in (f1, f2):
            with pytest.raises(InjectedDispatchError):
                f.result(timeout=10)
        # two consecutive dispatch failures tripped the bucket: the
        # third submit fails FAST with the tripped reason, untouched by
        # the 60s cooldown
        t0 = time.monotonic()
        with pytest.raises(BucketTripped, match="InjectedDispatchError"):
            q.submit(_mk(3))
        assert time.monotonic() - t0 < 1.0
    assert stats.breaker_trips == 1
    assert stats.breaker_fast_fails == 1
    assert chaos.dispatches(str(q._key_for(_mk(1), 0)[0])) == 2


def test_flush_failure_does_not_wedge_and_prunes_pending():
    """Satellites: an exception-riddled flush must leave `_force`
    reset (a wedged `_force` would break every later deadline flush)
    and `_pending` must never accumulate empty bucket entries."""
    chaos = DispatchChaos(fail_first=99)
    q = FleetQueue(OPT64, max_batch=64, max_wait_s=30.0, chaos=chaos)
    try:
        f1 = q.submit(_mk(1))
        q.flush()
        with pytest.raises(InjectedDispatchError):
            f1.result(timeout=10)
        assert not q._force
        assert q._pending == {}  # the emptied bucket was pruned
        # distinct shapes through the queue never leak empty entries
        futs = [q.submit(_mk(2, n_pt=20)), q.submit(_mk(3, n_pt=40)),
                q.submit(_mk(4, n_pt=70))]
        q.flush()
        for f in futs:
            with pytest.raises(InjectedDispatchError):
                f.result(timeout=10)
        assert q._pending == {}
        assert not q._force
    finally:
        q.close()
    q.close()  # idempotent: a second close is a no-op, not an error
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(_mk(5))


def test_deadline_expiring_during_failed_dispatch_counts_as_miss():
    """A problem dispatched in time whose batch then fails after the
    deadline passed gets the dispatch error (the real diagnostic) but
    the expired deadline still lands in the deadline_miss counter —
    the event must not vanish from FleetStats."""
    stats = FleetStats()
    chaos = DispatchChaos(fail_first=99, delay_s=0.3)
    with FleetQueue(OPT64, max_batch=1, max_wait_s=0.0, stats=stats,
                    chaos=chaos) as q:
        fut = q.submit(_mk(1), deadline_s=0.1)
        with pytest.raises(InjectedDispatchError):
            fut.result(timeout=10)
    assert stats.deadline_misses == 1
    assert stats.sheds == 0  # it WAS dispatched — a miss, not a shed


# ---------------------------------------------------------------------------
# Fault-plan lowering / stacking + dispatch chaos determinism
# ---------------------------------------------------------------------------

def test_fault_plan_lowering_and_stacking():
    plan = make_nan_burst(6, [1, 4], start=2, stop=5, n_points=3,
                          dtype=np.float64)
    # permutation rides exactly like the edges it follows
    perm = np.asarray([5, 4, 3, 2, 1, 0])
    low = lower_fault_plan(plan, n_edges=8, n_points=4, dtype=np.float64,
                           perm=perm)
    assert low.edge_nan.shape == (8,)
    assert np.isnan(low.edge_nan[perm.argsort()[1]])  # edge 1 followed
    assert np.isnan(low.edge_nan[perm.argsort()[4]])
    assert np.count_nonzero(np.isnan(low.edge_nan)) == 2
    assert not np.isnan(low.edge_nan[6:]).any()  # padding stays zero
    assert low.point_crush.shape == (4,) and low.point_crush[3] == 0.0
    assert tuple(low.window) == (2, 5)
    # a plan built without a point axis lowers to zeros
    edge_only = make_nan_burst(6, [0], start=0, stop=1, dtype=np.float64)
    low2 = lower_fault_plan(edge_only, n_edges=8, n_points=4,
                            dtype=np.float64)
    assert low2.point_crush.shape == (4,)
    # too-big plans are rejected, not truncated
    with pytest.raises(ValueError, match="point_crush"):
        lower_fault_plan(plan, n_edges=8, n_points=2, dtype=np.float64)
    with pytest.raises(ValueError, match="edge_nan"):
        lower_fault_plan(plan, n_edges=4, n_points=4, dtype=np.float64)

    inert = inert_fault_plan(8, 4, np.float64)
    assert not np.isnan(inert.edge_nan).any()
    assert tuple(inert.window) == (0, 0)
    closed = close_fault_window(low)
    assert tuple(closed.window) == (0, 0)
    assert np.isnan(closed.edge_nan).any()  # only the gate changed

    stack = stack_fault_plans([low, inert, closed])
    assert isinstance(stack, FaultPlan)
    assert stack.edge_nan.shape == (3, 8)
    assert stack.window.shape == (3, 2)
    assert stack.offset.shape == (3,)
    with pytest.raises(ValueError):
        stack_fault_plans([])


def test_dispatch_chaos_seeded_determinism():
    a = DispatchChaos(fail_rate=0.5, seed=3)
    b = DispatchChaos(fail_rate=0.5, seed=3)

    def pattern(chaos, bucket, n=32):
        out = []
        for _ in range(n):
            try:
                chaos.before_dispatch(bucket)
                out.append(False)
            except InjectedDispatchError:
                out.append(True)
        return out

    pa = pattern(a, "bucket_x")
    assert pa == pattern(b, "bucket_x")  # same seed: identical sequence
    assert any(pa) and not all(pa)
    c = DispatchChaos(fail_rate=0.5, seed=4)
    assert pattern(c, "bucket_x") != pa  # different seed: different run
    # bucket restriction: non-matching buckets are untouched
    d = DispatchChaos(fail_first=99, buckets=frozenset({"only_this"}))
    d.before_dispatch("something_else")
    with pytest.raises(InjectedDispatchError):
        d.before_dispatch("only_this")
    with pytest.raises(ValueError):
        DispatchChaos(fail_rate=1.5)


# ---------------------------------------------------------------------------
# Stats + aggregate CLI satellites
# ---------------------------------------------------------------------------

def test_fleet_stats_resilience_counters():
    s = FleetStats()
    s.record_shed(2)
    s.record_deadline_miss()
    s.record_retry(1)
    s.record_retry(1)
    s.record_retry(2)
    s.record_reject()
    for ev in ("trip", "probe", "recover", "fast_fail"):
        s.record_breaker(ev)
    s.record_depth(5)
    s.record_depth(3)  # peak keeps the max
    d = s.as_dict()
    assert d["sheds"] == 2 and d["deadline_misses"] == 1
    assert d["retries"] == 3
    assert d["retries_by_rung"] == {"1": 2, "2": 1}
    assert d["rejected"] == 1
    assert d["breaker_trips"] == 1 and d["breaker_probes"] == 1
    assert d["breaker_recoveries"] == 1 and d["breaker_fast_fails"] == 1
    assert d["queue_depth_peak"] == 5
    assert "resilience:" in s.report() and "breaker:" in s.report()
    with pytest.raises(ValueError):
        s.record_breaker("nope")
    # a fresh stats object keeps the report free of resilience noise
    assert "resilience:" not in FleetStats().report()


def test_aggregate_cli_reports_resilience_counters():
    from megba_tpu.observability.report import SolveReport
    from megba_tpu.observability.summarize import aggregate_reports

    stats = {"sheds": 1, "retries": 2, "deadline_misses": 1,
             "rejected": 3, "breaker_trips": 1, "breaker_probes": 1,
             "breaker_recoveries": 1, "breaker_fast_fails": 4}
    reps = [
        SolveReport(problem={}, config={}, backend={}, phases={},
                    result={"status_name": "converged"},
                    fleet={"bucket": "b", "latency_s": 0.1, "attempts": 1,
                           "rung": 0, "stats": {}},
                    created_unix=100.0),
        SolveReport(problem={}, config={}, backend={}, phases={},
                    result={"status_name": "recovered"},
                    fleet={"bucket": "b", "latency_s": 0.2, "attempts": 2,
                           "rung": 1, "stats": stats},
                    created_unix=101.0),
    ]
    out = aggregate_reports(reps)
    assert "status recovered: 1" in out
    assert "resilience: 1 escalated attempts (max rung 1)" in out
    assert "2 retries" in out and "1 shed" in out
    assert "1 deadline-missed" in out and "3 rejected" in out
    assert "breaker: 1 trips / 1 probes / 1 recoveries / 4 fast-fails" \
        in out
    # plain (non-fleet) streams keep the pre-resilience shape
    plain = aggregate_reports([SolveReport(
        problem={}, config={}, backend={}, phases={},
        result={"status_name": "converged"}, created_unix=1.0)])
    assert "resilience:" not in plain


# ---------------------------------------------------------------------------
# Chaos paths that run real solves (slow: full lane only)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_poisoned_lane_isolated_bitwise_and_heals_under_guards():
    """The batched chaos contract: one NaN-poisoned lane ends unusable
    (guards off) or RECOVERED (guards on) while its batch-mates stay
    BITWISE identical to the unpoisoned control — the same faulted
    program with the poison window closed, so the mates' operands are
    bit-identical and only the poisoned lane's plan differs."""
    clean0, clean1 = _mk(3, 32), _mk(7, 29)
    poisoned = _poison(_mk(11, 31))
    fleet = [clean0, poisoned, clean1]
    control = [clean0,
               dataclasses.replace(
                   poisoned,
                   fault_plan=close_fault_window(poisoned.fault_plan)),
               clean1]

    got = solve_many(fleet, OPT64)
    ref = solve_many(control, OPT64)
    assert got[0].shape == got[1].shape == got[2].shape  # one bucket

    # guards off: the poisoned lane is unusable and says so
    assert got[1].status in {int(SolveStatus.STALLED),
                             int(SolveStatus.FATAL_NONFINITE)}
    assert not np.isfinite(float(got[1].cost))
    assert np.isfinite(float(ref[1].cost))  # control really is clean

    # batch-mates: bitwise identical to the unpoisoned run
    for i in (0, 2):
        assert got[i].cameras.tobytes() == ref[i].cameras.tobytes()
        assert got[i].points.tobytes() == ref[i].points.tobytes()
        assert got[i].cost.tobytes() == ref[i].cost.tobytes()
        assert got[i].iterations == ref[i].iterations

    # guards on (= the ladder's rung-1 option): the same poison heals
    opt_guarded = EscalationPolicy().option_for_rung(OPT64, 1)
    healed = solve_many(fleet, opt_guarded)
    assert healed[1].status == int(SolveStatus.RECOVERED)
    assert np.isfinite(float(healed[1].cost))
    assert healed[1].recoveries >= 1


@pytest.mark.slow
def test_queue_escalation_heals_poisoned_problem():
    """End-to-end ladder: rung 0 (as submitted, guards off) ends
    STALLED/non-finite -> requeued at rung 1 (guards + inflated
    damping) -> RECOVERED, with per-attempt history on the result and
    <= 1 compile per (bucket, rung) certified by the retrace
    sentinel."""
    from megba_tpu.analysis import retrace

    clean0, clean1 = _mk(3, 32), _mk(7, 29)
    poisoned = _poison(_mk(11, 31))
    stats = FleetStats()

    base = retrace.snapshot()
    with FleetQueue(OPT64, max_batch=8, max_wait_s=30.0, stats=stats,
                    escalation=EscalationPolicy(
                        backoff_base_s=0.01, seed=0)) as q:
        futs = [q.submit(p) for p in (clean0, poisoned, clean1)]
        q.flush()
        got = [f.result(timeout=600) for f in futs]

    # escalated re-solves never retraced an already-compiled program:
    # <= 1 compile per (bucket program, rung option) signature
    new = {k: v - base.get(k, 0) for k, v in retrace.snapshot().items()
           if k[0].startswith("serving.batched") and v > base.get(k, 0)}
    assert all(delta <= 1 for delta in new.values()), new

    for g in (got[0], got[2]):  # clean problems: untouched by the chaos
        assert g.attempts == 1 and g.rung == 0 and g.history == []
        assert np.isfinite(float(g.cost))
    healed = got[1]
    assert healed.status == int(SolveStatus.RECOVERED)
    assert healed.attempts == 2 and healed.rung == 1
    assert len(healed.history) == 1
    assert healed.history[0]["rung"] == 0
    assert healed.history[0]["status"] in {
        int(SolveStatus.STALLED), int(SolveStatus.FATAL_NONFINITE)}
    assert healed.history[0]["error"] is None
    assert np.isfinite(float(healed.cost))
    assert stats.retries == 1 and stats.retries_by_rung == {1: 1}


@pytest.mark.slow
def test_queue_dispatch_error_escalates_then_succeeds():
    """Dispatch-level exceptions ride the same ladder: chaos kills the
    first dispatch, the retry (rung 1) solves, and the history records
    the error string."""
    stats = FleetStats()
    chaos = DispatchChaos(fail_first=1)
    with FleetQueue(OPT64, max_batch=1, max_wait_s=0.0, stats=stats,
                    chaos=chaos,
                    escalation=EscalationPolicy(backoff_base_s=0.01)) as q:
        r = q.submit(_mk(3, 32)).result(timeout=600)
    assert r.attempts == 2 and r.rung == 1
    assert "InjectedDispatchError" in r.history[0]["error"]
    assert np.isfinite(float(r.cost))
    assert stats.retries == 1


@pytest.mark.slow
def test_deadline_missed_result_is_flagged_not_silent():
    """A problem dispatched in time but completing late is delivered
    flagged `deadline_missed` (chaos delay makes 'late' deterministic
    instead of racing the wall clock)."""
    stats = FleetStats()
    chaos = DispatchChaos(delay_s=1.2)
    with FleetQueue(OPT64, max_batch=1, max_wait_s=0.0, stats=stats,
                    chaos=chaos) as q:
        r = q.submit(_mk(3, 32), deadline_s=1.0).result(timeout=600)
    assert r.deadline_missed
    assert r.latency_s >= 1.0
    assert np.isfinite(float(r.cost))  # delivered, not discarded
    assert stats.deadline_misses == 1 and stats.sheds == 0


@pytest.mark.slow
def test_breaker_half_open_probe_recovers_bucket():
    """Trip the bucket with injected failures, wait out the cooldown,
    and watch the half-open probe batch close the breaker again."""
    stats = FleetStats()
    chaos = DispatchChaos(fail_first=2)
    with FleetQueue(OPT64, max_batch=1, max_wait_s=0.0, stats=stats,
                    chaos=chaos,
                    breaker=BreakerPolicy(trip_after=2,
                                          cooldown_s=0.3)) as q:
        bucket = str(q._key_for(_mk(3, 32), 0)[0])
        for seed in (1, 2):
            with pytest.raises(InjectedDispatchError):
                q.submit(_mk(seed, 32)).result(timeout=10)
        assert q.breaker.state(bucket) is BreakerState.OPEN
        with pytest.raises(BucketTripped):
            q.submit(_mk(5, 32))  # fail-fast while cooling down
        time.sleep(0.35)
        r = q.submit(_mk(3, 32)).result(timeout=600)  # the probe
        assert np.isfinite(float(r.cost))
        assert q.breaker.state(bucket) is BreakerState.CLOSED
    assert stats.breaker_trips == 1
    assert stats.breaker_probes == 1
    assert stats.breaker_recoveries == 1
    assert stats.breaker_fast_fails == 1
