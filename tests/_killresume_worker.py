"""Kill-resume worker: one checkpointed BA solve, then dump the result.

Run as `python tests/_killresume_worker.py <checkpoint.npz> <result.npz>`.
The problem is fully seeded, so two complete runs (interrupted-and-
resumed vs uninterrupted) must produce BITWISE identical parameters and
traces — the contract tests/test_killresume.py pins with a real SIGKILL
(robustness/harness.py).  Everything that could differ between runs is
pinned here: backend, device count, x64, the persistent compile cache.
"""

import os
import sys

# Runnable from any cwd: the repo root is this file's parent's parent.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

from megba_tpu.utils.backend import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

from megba_tpu.algo.checkpointed import solve_checkpointed  # noqa: E402
from megba_tpu.common import (  # noqa: E402
    AlgoOption,
    JacobianMode,
    ProblemOption,
    SolverOption,
)
from megba_tpu.io.synthetic import make_synthetic_bal  # noqa: E402
from megba_tpu.observability.trace import TRACE_FIELDS  # noqa: E402
from megba_tpu.ops.residuals import make_residual_jacobian_fn  # noqa: E402


def main(checkpoint_path: str, result_path: str) -> None:
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=7, param_noise=4e-2, pixel_noise=0.3)
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=8, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(max_iter=60, tol=1e-12,
                                   refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    res = solve_checkpointed(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
        checkpoint_path=checkpoint_path, checkpoint_every=2)
    payload = {
        "cameras": np.asarray(res.cameras),
        "points": np.asarray(res.points),
        "cost": np.asarray(res.cost),
        "iterations": np.asarray(int(res.iterations)),
        "accepted": np.asarray(int(res.accepted)),
        "status": np.asarray(int(res.status)),
    }
    for field in TRACE_FIELDS:
        payload[f"trace_{field}"] = np.asarray(getattr(res.trace, field))
    tmp = result_path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, result_path)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
