"""Pluggable Schur preconditioners (solver/precond.py, ISSUE 7).

Contracts pinned here:

- SPD + spectral sanity: every operator family materialises to a
  symmetric positive-definite M⁻¹ on a real (damped) Schur system, and
  the two-level cycle's coarse operator A_c is EXACTLY the Galerkin
  projection R S_d Rᵀ of the damped Schur complement (dense parity,
  f64), with G = S_d Rᵀ and the full cycle matching the explicit
  Rᵀ A_c⁺ R + Pᵀ D⁻¹ P formula.
- Parity suite: block-Jacobi vs Neumann vs two-level reach the same
  optimum (rtol 1e-6) on the same LM budget, single-device AND
  world-2; the stronger operators spend strictly fewer PCG iterations
  in their winning regime; `precond="jacobi"` is BITWISE the
  historical solver.
- Fallback ladder: a poisoned coarse build degrades the cycle to the
  base apply bitwise, the degrade is enum-coded per level in
  `precond_fallback`, and encode/decode round-trips.
- Cluster plan: the greedy aggregation partitions all cameras, the
  pc/ec index streams are mutually consistent, shard grouping is
  self-consistent, and the plan rides the content-fingerprint cache.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    ComputeKind,
    JacobianMode,
    PrecondKind,
    PreconditionerKind,
    ProblemOption,
    SolverOption,
    validate_options,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.linear_system import build_schur_system, weight_system_inputs
from megba_tpu.linear_system.builder import damp_blocks
from megba_tpu.core.fm import block_inv_fm, coupling_rows, damp_rows_fm
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.ops.segtiles import (
    build_camera_clusters,
    build_cluster_plan,
    cached_cluster_plan,
    device_cluster_plan,
)
from megba_tpu.solve import flat_solve
from megba_tpu.solver.pcg import schur_pcg_solve
from megba_tpu.solver.precond import (
    FALLBACK_BLOCK_RADIX,
    block_inv,
    build_two_level_coarse,
    cam_block_matvec,
    decode_precond_fallback,
    encode_precond_fallback,
    make_schur_preconditioner,
    two_level_cycle,
)

CD, PD = 9, 3


def _system(num_cameras=7, num_points=40, seed=2, dtype=np.float64):
    s = make_synthetic_bal(num_cameras=num_cameras, num_points=num_points,
                           obs_per_point=4, seed=seed, dtype=dtype)
    cams, pts = jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T)
    ci, pi = jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx)
    obs = jnp.asarray(s.obs.T)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    r, Jc, Jp = f(cams[:, ci], pts[:, pi], obs)
    r, Jc, Jp = weight_system_inputs(r, Jc, Jp, ci, pi,
                                     jnp.ones(obs.shape[1]))
    system = build_schur_system(r, Jc, Jp, ci, pi, num_cameras, num_points)
    return s, system, Jc, Jp, ci, pi


def _dense_schur(s, system, Jc, Jp, region):
    """Explicit damped Schur complement S_d [Nc*cd, Nc*cd] (f64)."""
    Nc = system.Hpp.shape[0]
    Np = system.Hll.shape[1]
    od = Jc.shape[0] // CD
    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_rows_fm(system.Hll, region)
    Hinv = np.asarray(block_inv_fm(Hll_d))
    W = np.asarray(coupling_rows(Jc, Jp, od))
    S = np.zeros((Nc * CD, Nc * CD))
    for i in range(Nc):
        S[i * CD:(i + 1) * CD, i * CD:(i + 1) * CD] = np.asarray(Hpp_d[i])
    Hpl = np.zeros((Nc * CD, Np * PD))
    for e in range(len(s.cam_idx)):
        c, p = int(s.cam_idx[e]), int(s.pt_idx[e])
        Hpl[c * CD:(c + 1) * CD, p * PD:(p + 1) * PD] += (
            W[:, e].reshape(CD, PD))
    Hll_inv_dense = np.zeros((Np * PD, Np * PD))
    for p in range(Np):
        Hll_inv_dense[p * PD:(p + 1) * PD, p * PD:(p + 1) * PD] = (
            Hinv[:, p].reshape(PD, PD))
    return S - Hpl @ Hll_inv_dense @ Hpl.T, Hpp_d, jnp.asarray(
        block_inv_fm(Hll_d)), W


def _materialize(apply_fn, n_cams):
    """Columns of M⁻¹ through the feature-major apply ([cd, Nc] rows)."""
    cols = []
    for e in np.eye(n_cams * CD):
        rfm = jnp.asarray(e.reshape(n_cams, CD).T)
        cols.append(np.asarray(apply_fn(rfm)).T.reshape(-1))
    return np.stack(cols, axis=1)


# ------------------------------------------------------ dense parity / SPD


def test_two_level_coarse_is_exact_galerkin_and_cycle_matches_formula():
    s, system, Jc, Jp, ci, pi = _system()
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(50.0)
    S, Hpp_d, Hll_inv, W = _dense_schur(s, system, Jc, Jp, region)
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, Nc, system.Hll.shape[1])
    dplan = device_cluster_plan(plan)
    C = plan.num_clusters
    coarse = build_two_level_coarse(Hpp_d, Hll_inv, jnp.asarray(W), Jc, Jp,
                                    dplan, ComputeKind.EXPLICIT)
    assert bool(coarse.ok)
    # Explicit R: piecewise-constant aggregation at scalar granularity.
    R = np.zeros((C * CD, Nc * CD))
    for n in range(Nc):
        I = plan.cluster[n]
        R[I * CD:(I + 1) * CD, n * CD:(n + 1) * CD] = np.eye(CD)
    np.testing.assert_allclose(np.asarray(coarse.coarse_matrix), R @ S @ R.T,
                               atol=1e-9 * np.abs(S).max())
    G_ref = S @ R.T
    G_impl = np.zeros_like(G_ref)
    Gd = np.asarray(coarse.G)
    for a in range(CD):
        for n in range(Nc):
            G_impl[n * CD + a, :] = Gd[a, n].reshape(-1)
    np.testing.assert_allclose(G_impl, G_ref, atol=1e-9 * np.abs(S).max())

    # Full cycle vs the explicit symmetric multiplicative formula, with
    # the SAME filtered pseudo-inverse on both sides.
    binv = block_inv(Hpp_d)
    base = lambda x: cam_block_matvec(binv, x)
    M_impl = _materialize(lambda r: two_level_cycle(coarse, base, r), Nc)
    lam, Q = np.linalg.eigh(R @ S @ R.T)
    keep = lam > 1e-5 * lam.max()
    Aplus = (Q[:, keep] / lam[keep]) @ Q[:, keep].T
    D_inv = np.zeros((Nc * CD, Nc * CD))
    for n in range(Nc):
        D_inv[n * CD:(n + 1) * CD, n * CD:(n + 1) * CD] = np.asarray(binv[n])
    P = np.eye(Nc * CD) - S @ R.T @ Aplus @ R
    M_ref = R.T @ Aplus @ R + P.T @ D_inv @ P
    np.testing.assert_allclose(M_impl, M_ref,
                               atol=1e-10 * np.abs(M_ref).max())


@pytest.mark.parametrize("kind", [PrecondKind.JACOBI, PrecondKind.NEUMANN,
                                  PrecondKind.TWO_LEVEL])
def test_preconditioner_is_spd(kind):
    s, system, Jc, Jp, ci, pi = _system()
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(50.0)
    S, Hpp_d, Hll_inv, W = _dense_schur(s, system, Jc, Jp, region)
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, Nc, system.Hll.shape[1])
    Snp = S

    def s_matvec(p):
        flat = np.asarray(p).T.reshape(-1)
        return jnp.asarray((Snp @ flat).reshape(Nc, CD).T)

    apply_fn, code = make_schur_preconditioner(
        kind, PreconditionerKind.HPP, Hpp_d, Hll_inv, jnp.asarray(W),
        Jc, Jp, ci, pi, Nc, ComputeKind.EXPLICIT, None, False,
        neumann_order=2, cluster_plan=device_cluster_plan(plan),
        s_matvec=s_matvec)
    M = _materialize(apply_fn, Nc)
    sym_err = np.abs(M - M.T).max() / np.abs(M).max()
    assert sym_err < 1e-12
    ev = np.linalg.eigvalsh(0.5 * (M + M.T))
    assert ev.min() > 0, f"{kind}: M⁻¹ not PD (min eig {ev.min():.3e})"
    assert int(code) == 0


def test_jacobi_family_is_bitwise_the_block_inverse():
    # The extracted JACOBI baseline must be EXACTLY the historical
    # apply: cam_block_matvec(block_inv(Hpp_d), r), bit for bit.
    s, system, Jc, Jp, ci, pi = _system(num_cameras=5, num_points=25,
                                        seed=4)
    Nc = system.Hpp.shape[0]
    Hpp_d = damp_blocks(system.Hpp, jnp.asarray(80.0))
    Hll_inv = block_inv_fm(damp_rows_fm(system.Hll, jnp.asarray(80.0)))
    apply_fn, code = make_schur_preconditioner(
        PrecondKind.JACOBI, PreconditionerKind.HPP, Hpp_d, Hll_inv,
        None, Jc, Jp, ci, pi, Nc, ComputeKind.IMPLICIT, None, False)
    rng = np.random.default_rng(0)
    r = jnp.asarray(rng.standard_normal((CD, Nc)))
    want = cam_block_matvec(block_inv(Hpp_d), r)
    assert np.array_equal(np.asarray(apply_fn(r)), np.asarray(want))
    assert int(code) == 0


# --------------------------------------------------------- parity suite


def _solve(s, kind, world_size=1, max_iter=12, **skw):
    option = ProblemOption(
        world_size=world_size,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-9,
                               epsilon2=1e-12),
        solver_option=SolverOption(max_iter=200, tol=1e-10,
                                   tol_relative=True, refuse_ratio=1e30,
                                   precond=kind, **skw))
    return flat_solve(make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL),
                      s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                      option)


def test_parity_jacobi_neumann_two_level_single_device():
    s = make_synthetic_bal(num_cameras=10, num_points=60, obs_per_point=5,
                           seed=0, param_noise=5e-2, pixel_noise=0.3)
    jac = _solve(s, PrecondKind.JACOBI)
    neu = _solve(s, PrecondKind.NEUMANN, neumann_order=2)
    two = _solve(s, PrecondKind.TWO_LEVEL)
    np.testing.assert_allclose(float(neu.cost), float(jac.cost), rtol=1e-6)
    np.testing.assert_allclose(float(two.cost), float(jac.cost), rtol=1e-6)
    # The stronger operators spend strictly fewer inner iterations on
    # the same trajectory budget.
    assert int(neu.pcg_iterations) < int(jac.pcg_iterations)
    assert int(two.pcg_iterations) < int(jac.pcg_iterations)


@pytest.mark.slow  # two fresh SPMD LM compiles — cache-cold this is
# minutes; the full suite (scripts/run_tests.sh) runs it, tier-1 skips
def test_parity_world2_matches_single_device():
    s = make_synthetic_bal(num_cameras=10, num_points=60, obs_per_point=5,
                           seed=3, param_noise=5e-2, pixel_noise=0.3)
    for kind in (PrecondKind.NEUMANN, PrecondKind.TWO_LEVEL):
        one = _solve(s, kind, world_size=1, max_iter=8)
        two = _solve(s, kind, world_size=2, max_iter=8)
        np.testing.assert_allclose(float(two.cost), float(one.cost),
                                   rtol=1e-6)
        assert int(two.pcg_iterations) == int(one.pcg_iterations)


def test_strict_iteration_decrease_isolated_solve():
    # One reduced solve at moderate damping, tight relative tolerance —
    # the regime where the plateau lives; both stronger operators must
    # STRICTLY beat block-Jacobi's iteration count.
    s, system, Jc, Jp, ci, pi = _system(num_cameras=12, num_points=70,
                                        seed=1)
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, 12, 70)
    region = jnp.asarray(100.0)
    kw = dict(max_iter=500, tol=1e-10, tol_relative=True, refuse_ratio=1e30)
    jac = schur_pcg_solve(system, Jc, Jp, ci, pi, region, **kw)
    neu = schur_pcg_solve(system, Jc, Jp, ci, pi, region,
                          precond=PrecondKind.NEUMANN, neumann_order=2, **kw)
    two = schur_pcg_solve(system, Jc, Jp, ci, pi, region,
                          precond=PrecondKind.TWO_LEVEL,
                          cluster_plan=device_cluster_plan(plan), **kw)
    assert int(neu.iterations) < int(jac.iterations)
    assert int(two.iterations) < int(jac.iterations)
    # All three land on the same solution (each run truncates at its
    # own tol-crossing iterate, so the agreement band is the truncation
    # error, not machine precision — the bitwise/rtol-1e-6 contracts
    # live in the LM-level parity tests above).
    scale = float(jnp.max(jnp.abs(jac.dx_cam)))
    np.testing.assert_allclose(np.asarray(neu.dx_cam),
                               np.asarray(jac.dx_cam), atol=1e-3 * scale)
    np.testing.assert_allclose(np.asarray(two.dx_cam),
                               np.asarray(jac.dx_cam), atol=1e-3 * scale)


# ------------------------------------------------------- fallback ladder


def test_fallback_encoding_round_trips():
    for block, coarse in ((0, 0), (1, 0), (0, 1), (37, 1), (65535, 3)):
        code = encode_precond_fallback(jnp.int32(block), jnp.int32(coarse))
        got = decode_precond_fallback(int(code))
        assert got == {"block": block, "coarse": coarse}
    # Saturation: a block count beyond the radix clamps instead of
    # corrupting the coarse field.
    code = encode_precond_fallback(jnp.int32(FALLBACK_BLOCK_RADIX + 5),
                                   jnp.int32(1))
    assert decode_precond_fallback(int(code)) == {
        "block": FALLBACK_BLOCK_RADIX - 1, "coarse": 1}


def test_poisoned_coarse_degrades_to_base_apply_bitwise():
    s, system, Jc, Jp, ci, pi = _system(num_cameras=5, num_points=25,
                                        seed=4)
    Nc = system.Hpp.shape[0]
    region = jnp.asarray(80.0)
    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_inv = block_inv_fm(damp_rows_fm(system.Hll, region))
    plan = build_cluster_plan(s.cam_idx, s.pt_idx, Nc, 25)
    dplan = device_cluster_plan(plan)
    # Poison one camera block -> NaN rides into A_c -> ok=False.
    Hpp_bad = Hpp_d.at[0, 0, 0].set(jnp.nan)
    apply_bad, code = make_schur_preconditioner(
        PrecondKind.TWO_LEVEL, PreconditionerKind.HPP, Hpp_bad, Hll_inv,
        None, Jc, Jp, ci, pi, Nc, ComputeKind.IMPLICIT, None, False,
        cluster_plan=dplan)
    assert decode_precond_fallback(int(code)) == {"block": 0, "coarse": 1}
    # The degraded apply IS the base block-Jacobi apply, bitwise (on
    # the finite blocks; block 0's NaN block inverse is NaN both ways).
    rng = np.random.default_rng(1)
    r = jnp.asarray(rng.standard_normal((CD, Nc)))
    want = cam_block_matvec(block_inv(Hpp_bad), r)
    got = apply_bad(r)
    np.testing.assert_array_equal(np.asarray(got)[:, 1:],
                                  np.asarray(want)[:, 1:])
    # Healthy build reports no fallback at either level.
    _, code_ok = make_schur_preconditioner(
        PrecondKind.TWO_LEVEL, PreconditionerKind.HPP, Hpp_d, Hll_inv,
        None, Jc, Jp, ci, pi, Nc, ComputeKind.IMPLICIT, None, False,
        cluster_plan=dplan)
    assert decode_precond_fallback(int(code_ok)) == {"block": 0, "coarse": 0}


def test_two_level_requires_cluster_plan():
    s, system, Jc, Jp, ci, pi = _system(num_cameras=5, num_points=25,
                                        seed=4)
    with pytest.raises(ValueError, match="cluster plan"):
        schur_pcg_solve(system, Jc, Jp, ci, pi, jnp.asarray(10.0),
                        precond=PrecondKind.TWO_LEVEL)


# ---------------------------------------------------------- validation


def test_validate_options_rejects_bad_precond_configs():
    def opt(**skw):
        return ProblemOption(solver_option=SolverOption(**skw))

    with pytest.raises(ValueError, match="neumann_order"):
        validate_options(opt(precond=PrecondKind.NEUMANN, neumann_order=0))
    with pytest.raises(ValueError, match="coarse_clusters"):
        validate_options(opt(precond=PrecondKind.TWO_LEVEL,
                             coarse_clusters=-1))
    with pytest.raises(ValueError, match="use_schur"):
        validate_options(dataclasses.replace(
            opt(precond=PrecondKind.NEUMANN), use_schur=False))
    validate_options(opt(precond=PrecondKind.TWO_LEVEL))  # clean


# --------------------------------------------------------- cluster plan


def test_camera_clusters_partition_and_cap():
    s = make_synthetic_bal(num_cameras=20, num_points=120, obs_per_point=4,
                           seed=5)
    cluster = build_camera_clusters(s.cam_idx, s.pt_idx, 20)
    assert cluster.shape == (20,)
    C = int(cluster.max()) + 1
    target = int(np.ceil(np.sqrt(20)))
    assert C >= target
    # Size cap: no cluster exceeds ceil(Nc / target).
    _, counts = np.unique(cluster, return_counts=True)
    assert counts.max() <= -(-20 // target)
    # Every camera (including any edge-less one) is assigned.
    assert np.all(cluster >= 0)


def test_cluster_plan_index_streams_are_consistent():
    s = make_synthetic_bal(num_cameras=9, num_points=50, obs_per_point=4,
                           seed=6)
    nE = len(s.cam_idx)
    # Pad the stream like the solver does, with a mask.
    pad = 8
    cam_idx = np.concatenate([s.cam_idx, np.zeros(pad, np.int32)])
    pt_idx = np.concatenate([s.pt_idx, np.zeros(pad, np.int32)])
    mask = np.concatenate([np.ones(nE), np.zeros(pad)])
    plan = build_cluster_plan(cam_idx, pt_idx, 9, 50, mask=mask,
                              world_size=2)
    C = plan.num_clusters
    # pc: every real edge maps to the incidence of ITS (point, cluster);
    # padding edges carry the inert slot.
    for e in range(nE):
        slot = plan.pc_slot[e]
        assert slot < plan.n_pc
        assert plan.pc_pt[slot] == pt_idx[e]
    assert np.all(plan.pc_slot[nE:] == plan.n_pc)
    # ec: Σ_e k_{pt(e)} real pairs; each pair couples an edge to an
    # incidence of the same point, and its segment is cam*C + cluster
    # of the slot.  Shard-local edge ids reassemble to global ones.
    # (An incidence's cluster is recoverable from any edge mapping to
    # it: cluster[cam_idx[e]] of an e with pc_slot[e] == slot.)
    slot_cluster = np.full(plan.n_pc, -1)
    for e in range(nE):
        slot_cluster[plan.pc_slot[e]] = plan.cluster[cam_idx[e]]
    ws, L = 2, plan.ec_edge.shape[0] // 2
    n_real = 0
    shard_edges = len(cam_idx) // ws
    for k in range(ws):
        for j in range(L):
            seg = plan.ec_seg[k * L + j]
            if seg == 9 * C:  # inert padding
                continue
            n_real += 1
            ge = int(plan.ec_edge[k * L + j]) + k * shard_edges
            slot = int(plan.ec_slot[k * L + j])
            assert plan.pc_pt[slot] == pt_idx[ge]
            assert seg == cam_idx[ge] * C + slot_cluster[slot]
    assert n_real == plan.n_ec


def test_cluster_plan_rides_content_cache():
    s = make_synthetic_bal(num_cameras=8, num_points=40, obs_per_point=4,
                           seed=7)
    (p1, d1), hit1 = cached_cluster_plan(s.cam_idx, s.pt_idx, 8, 40)
    (p2, d2), hit2 = cached_cluster_plan(s.cam_idx.copy(),
                                         s.pt_idx.copy(), 8, 40)
    assert not hit1 and hit2
    assert p1 is p2
    # A different target is a different plan.
    (_, _), hit3 = cached_cluster_plan(s.cam_idx, s.pt_idx, 8, 40, 4)
    assert not hit3
