"""Program-identity contract lane: stale-program / cache-split /
key-surface-drift.

Compile-free tier-1 units — every finding class the identity analyzer
knows gets a positive (fires on a handwritten fixture) AND a negative
(silent on the sanctioned variant), so a pass that silently stops
matching — or starts over-matching — breaks this suite rather than the
compile/artifact/bucket caches.  The seeded lint fixtures are pinned to
exact per-rule counts, and the package itself must stay at zero
findings.
"""

import os
import subprocess
import sys
import textwrap

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "lint_fixtures")
BAD = os.path.join(FIXTURES, "bad_identity.py")
GOOD = os.path.join(FIXTURES, "good_identity.py")
PACKAGE = os.path.join(os.path.dirname(__file__), "..", "megba_tpu")

IDENTITY_RULES = ["stale-program", "cache-split", "key-surface-drift"]

# Shared miniature of the repo's option/key machinery for inline
# fixtures.  Each test appends only the shape under scrutiny.
PRELUDE = """\
    import dataclasses
    import functools
    from typing import Optional

    import jax

    OBSERVABILITY_FIELDS = ("telemetry", "metrics")

    def static_key(*parts):
        return "|".join(repr(p) for p in parts)

    def strip_observability(option):
        if option.telemetry is not None or option.metrics:
            return dataclasses.replace(
                option, telemetry=None, metrics=False)
        return option

    @dataclasses.dataclass(frozen=True)
    class SolverOption:
        max_iter: int = 100
        bf16: bool = False

    @dataclasses.dataclass(frozen=True)
    class ProblemOption:
        dtype: str = "float32"
        solver_option: SolverOption = dataclasses.field(
            default_factory=SolverOption)
        telemetry: Optional[str] = None
        metrics: bool = False
    """


def _lint(*paths, rules=IDENTITY_RULES):
    from megba_tpu.analysis.lint import lint_paths

    return lint_paths(list(paths), rules=list(rules))


def _index(*paths):
    from megba_tpu.analysis.callgraph import PackageIndex

    return PackageIndex.build(list(paths))


def _src_index(tmp_path, source):
    """Write an inline fixture module (PRELUDE + `source`) and index it."""
    mod = tmp_path / "fixture_mod.py"
    mod.write_text(textwrap.dedent(PRELUDE) + textwrap.dedent(source))
    return _index(str(mod))


def _src_lint(tmp_path, source, rules=IDENTITY_RULES):
    mod = tmp_path / "fixture_mod.py"
    mod.write_text(textwrap.dedent(PRELUDE) + textwrap.dedent(source))
    return _lint(str(mod), rules=rules)


@pytest.fixture(scope="module")
def pkg_summary():
    from megba_tpu.analysis.identity import identity_summary

    return identity_summary(_index(PACKAGE))


@pytest.fixture(scope="module")
def bad_findings():
    return _lint(BAD)


# -------------------------------------------------- callgraph read sets


def test_attr_reads_records_full_dotted_chains(tmp_path):
    idx = _src_index(tmp_path, """\

        def reader(option):
            a = option.solver_option.bf16
            b = option.dtype
            return a, b
        """)
    info = idx.functions["fixture_mod.reader"]
    # chains are keyed by root name, stored relative to it
    assert "solver_option.bf16" in info.attr_reads["option"]
    assert "dtype" in info.attr_reads["option"]
    # outermost chain only — no suffix entries for the inner Attribute
    assert "solver_option" not in info.attr_reads["option"]


def test_assigns_records_dotted_aliases(tmp_path):
    idx = _src_index(tmp_path, """\

        def alias(option):
            solver_opt = option.solver_option
            return solver_opt.max_iter
        """)
    info = idx.functions["fixture_mod.alias"]
    assert info.assigns["solver_opt"] == "option.solver_option"
    assert "max_iter" in info.attr_reads["solver_opt"]


def test_read_resolution_through_alias_closure_and_cache(tmp_path):
    """flat_solve -> lru_cache alias -> builder -> nested closure: the
    closure's aliased sub-option read resolves to a dotted leaf path."""
    from megba_tpu.analysis.identity import identity_summary

    idx = _src_index(tmp_path, """\

        def _build(residual_jac_fn, option):
            solver_opt = option.solver_option

            def step(x):
                return x if solver_opt.bf16 else x * 2.0

            return jax.jit(step), static_key(residual_jac_fn, option)

        _cached_build = functools.lru_cache(8)(_build)

        def flat_solve(residual_jac_fn, x, option: ProblemOption):
            option = strip_observability(option)
            prog, key = _cached_build(residual_jac_fn, option)
            return prog(x), key
        """)
    s = identity_summary(idx)
    assert "fixture_mod.flat_solve" in s["entries"]
    assert s["cache_aliases"] == {
        "fixture_mod._cached_build": "fixture_mod._build"}
    assert "fixture_mod._build.step" in s["closure"]
    assert "fixture_mod._build.step" in s["reads"]["solver_option.bf16"]


# --------------------------------------------------- registry extraction


def test_registry_from_good_fixture():
    from megba_tpu.analysis.identity import identity_summary

    s = identity_summary(_index(GOOD))
    assert s["strip_fields"] == ("telemetry", "metrics")
    for leaf in ("dtype", "trace_dir", "telemetry", "metrics",
                 "solver_option.solver_kind", "solver_option.bf16"):
        assert leaf in s["leaf_paths"], leaf
    assert "solver_option" not in s["leaf_paths"]  # container, not leaf
    assert s["pragmas"]["lowering-relevant"] == [
        "solver_option.solver_kind"]
    assert s["pragmas"]["key-exempt"] == ["trace_dir"]


def test_strip_list_falls_back_to_helper_cleared_kwargs(tmp_path):
    """No OBSERVABILITY_FIELDS tuple: the strip-list is recovered from
    the declared strip helper's cleared replace kwargs."""
    from megba_tpu.analysis.identity import identity_summary

    mod = tmp_path / "fixture_mod.py"
    mod.write_text(textwrap.dedent("""\
        import dataclasses

        def _strip_telemetry(option):
            return dataclasses.replace(
                option, telemetry=None, metrics=False)
        """))
    s = identity_summary(_index(str(mod)))
    assert s["strip_fields"] == ("metrics", "telemetry")


# -------------------------------------------------------- stale-program


def test_stale_program_fires_on_bad_fixture(bad_findings):
    stale = [f for f in bad_findings if f.rule == "stale-program"]
    assert len(stale) == 2
    msgs = " | ".join(f.message for f in stale)
    assert "`telemetry` is read on the lowering path" in msgs
    assert "omits its option parameter `option`" in msgs


def test_stale_read_positive_and_consume_and_strip_negative(tmp_path):
    """Two lowering-path readers of the sink: the one that strips in
    the same function is exempt, the other flags at the read site."""
    findings = _src_lint(tmp_path, """\

        def flat_solve(residual_jac_fn, x, option: ProblemOption):
            sink = option.telemetry
            return x, sink

        def lower_bucket(residual_jac_fn, x, option: ProblemOption):
            sink = option.telemetry
            option = strip_observability(option)
            return x, sink
        """, rules=["stale-program"])
    assert len(findings) == 1
    assert "flat_solve" in findings[0].message
    assert "`telemetry`" in findings[0].message


def test_stale_key_omission_and_taint_fixpoint(tmp_path):
    """A static key fed only a derived local still counts as carrying
    the option (taint through `compare = strip_observability(option)`);
    a key omitting the option entirely flags."""
    findings = _src_lint(tmp_path, """\

        def good_key(residual_jac_fn, option):
            compare = strip_observability(option)
            return static_key(residual_jac_fn, compare)

        def bad_key(residual_jac_fn, option):
            return static_key(residual_jac_fn, "site")
        """, rules=["stale-program"])
    assert len(findings) == 1
    assert "bad_key" in findings[0].message


# ---------------------------------------------------------- cache-split


def test_cache_split_fires_on_bad_fixture(bad_findings):
    split = [f for f in bad_findings if f.rule == "cache-split"]
    assert len(split) == 2
    fields = " | ".join(f.message for f in split)
    assert "`debug_port`" in fields
    assert "`solver_option.scratch_limit_mb`" in fields
    # strip-listed fields are never flagged as split hazards
    assert "`telemetry`" not in fields and "`metrics`" not in fields


def test_cache_split_pragma_hatches(tmp_path):
    """An unread field flags; the same shape under either declared-
    intent pragma is silent."""
    body = """\

        @dataclasses.dataclass(frozen=True)
        class AlgoOption:
            quiet_knob: int = 0{pragma}

        def flat_solve(x, option: ProblemOption):
            return x if option.dtype else x
        """
    flagged = _src_lint(tmp_path, body.format(pragma=""),
                        rules=["cache-split"])
    assert any("`algo_option.quiet_knob`" in f.message for f in flagged)
    for hatch in ("  # megba: lowering-relevant(algo_option.quiet_knob)",
                  "  # megba: key-exempt(algo_option.quiet_knob)"):
        silent = _src_lint(tmp_path, body.format(pragma=hatch),
                           rules=["cache-split"])
        assert not any("quiet_knob" in f.message for f in silent), hatch


# --------------------------------------------------- key-surface-drift


def test_drift_partial_strip_on_bad_fixture(bad_findings):
    msgs = [f.message for f in bad_findings
            if f.rule == "key-surface-drift"]
    partial = [m for m in msgs if "partial observability strip" in m]
    assert len(partial) == 1
    assert "clears ['telemetry']" in partial[0]
    assert "['metrics']" in partial[0]


def test_drift_nonconforming_helper_on_bad_fixture(bad_findings):
    msgs = [f.message for f in bad_findings
            if f.rule == "key-surface-drift"]
    assert any("strip helper" in m
               and "clears neither the full strip-list" in m
               for m in msgs)


def test_drift_hardcoded_exclusion_witness(bad_findings):
    """The drift witness names both disagreeing surfaces AND the
    registry to derive from."""
    msgs = [f.message for f in bad_findings
            if f.rule == "key-surface-drift"]
    hard = [m for m in msgs if "hardcoded key-exclusion" in m]
    assert len(hard) == 1
    assert "['telemetry']" in hard[0]
    assert "['metrics', 'telemetry']" in hard[0]
    assert "OBSERVABILITY_FIELDS" in hard[0]


def test_drift_exclusion_equal_to_registry_is_silent(tmp_path):
    findings = _src_lint(tmp_path, """\

        def _config_mismatches(recorded):
            return [k for k in recorded
                    if k not in ("telemetry", "metrics")]
        """, rules=["key-surface-drift"])
    assert findings == []


def test_drift_unstripped_cache_front(bad_findings, tmp_path):
    msgs = [f.message for f in bad_findings
            if f.rule == "key-surface-drift"]
    assert any("fronts the memoised program cache" in m for m in msgs)
    # the stripped front in the same shape is silent
    findings = _src_lint(tmp_path, """\

        def _build(residual_jac_fn, option):
            def fn(x):
                return x * option.solver_option.max_iter
            return jax.jit(fn), static_key(residual_jac_fn, option)

        _cached_build = functools.lru_cache(8)(_build)

        def flat_solve(residual_jac_fn, x, option: ProblemOption):
            option = strip_observability(option)
            return _cached_build(residual_jac_fn, option)
        """, rules=["key-surface-drift"])
    assert not any("fronts the memoised" in f.message for f in findings)


def test_drift_operand_branch_positive_and_is_none_negative(tmp_path):
    findings = _src_lint(tmp_path, """\

        def _build(option):
            def fn(x, mask, edge_mask):
                if mask is None:  # sanctioned presence check
                    return x
                if edge_mask:  # operand-as-static
                    return x * 2.0
                return x
            return jax.jit(fn)
        """, rules=["key-surface-drift"])
    operand = [f for f in findings if "operand" in f.message]
    assert len(operand) == 1
    assert "`edge_mask`" in operand[0].message
    assert "operand-as-static" in operand[0].message


def test_drift_pragma_contradiction_and_unknown_field(tmp_path):
    findings = _src_lint(tmp_path, """\

        @dataclasses.dataclass(frozen=True)
        class AlgoOption:
            # megba: lowering-relevant(algo_option.torn) key-exempt(algo_option.torn)
            torn: int = 0
            # megba: key-exempt(algo_option.vanished_field)
            here: int = 1
        """, rules=["key-surface-drift"])
    msgs = [f.message for f in findings]
    assert any("carries BOTH" in m and "`algo_option.torn`" in m
               for m in msgs)
    assert any("not a declared option field" in m
               and "`algo_option.vanished_field`" in m for m in msgs)


# ------------------------------------------ fixtures, package, surfaces


def test_bad_fixture_pinned_per_rule_counts(bad_findings):
    by_rule = {r: sum(1 for f in bad_findings if f.rule == r)
               for r in IDENTITY_RULES}
    assert by_rule == {"stale-program": 2, "cache-split": 2,
                       "key-surface-drift": 5}


def test_good_fixture_stays_silent():
    assert _lint(GOOD) == []


def test_package_zero_findings():
    """The contract holds on the real package: all three identity rules
    are clean on megba_tpu/ (the lane-7 acceptance gate)."""
    findings = _lint(PACKAGE)
    assert findings == [], [f.format() for f in findings]


def test_package_key_surfaces_agree(pkg_summary):
    """The four keying surfaces derive from ONE registry: the analyzer
    extracts exactly megba_tpu.common.OBSERVABILITY_FIELDS, and every
    lowering entry named by the contract is discovered."""
    from megba_tpu.common import OBSERVABILITY_FIELDS

    assert pkg_summary["strip_fields"] == tuple(OBSERVABILITY_FIELDS)
    entries = set(pkg_summary["entries"])
    for q in ("megba_tpu.solve.flat_solve",
              "megba_tpu.parallel.mesh.distributed_lm_solve",
              "megba_tpu.serving.compile_pool.batched_solve_program",
              "megba_tpu.serving.compile_pool.lower_bucket",
              "megba_tpu.models.pgo.solve_pgo"):
        assert q in entries, q


def test_package_unread_fields_all_declared(pkg_summary):
    """Every keyed-but-never-lowering-read field carries a declared-
    intent pragma — the cache-split rule is silent for the RIGHT
    reason, not because the read-set over-resolves."""
    strip = set(pkg_summary["strip_fields"])
    declared = (set(pkg_summary["pragmas"]["lowering-relevant"])
                | set(pkg_summary["pragmas"]["key-exempt"]))
    unread = {leaf for leaf in pkg_summary["leaf_paths"]
              if leaf not in strip
              and leaf.split(".")[-1] not in strip
              and leaf not in pkg_summary["reads"]}
    assert unread == declared
    # and the declarations are disjoint (no contradictions)
    assert not (set(pkg_summary["pragmas"]["lowering-relevant"])
                & set(pkg_summary["pragmas"]["key-exempt"]))


def test_no_key_exempt_pragmas_in_serving():
    """serving/ may not wave fields out of the key surface: key-exempt
    declarations live with the option definitions (megba_tpu/common.py),
    each with a stated reason."""
    serving = os.path.join(PACKAGE, "serving")
    offenders = []
    for dirpath, _dirs, files in os.walk(serving):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, start=1):
                    if "megba:" in line and "key-exempt(" in line:
                        offenders.append(f"{path}:{lineno}")
    assert offenders == []


def test_cli_exit_codes_per_rule():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    root = os.path.dirname(PACKAGE)
    for rule in IDENTITY_RULES:
        res = subprocess.run(
            [sys.executable, "-m", "megba_tpu.analysis.lint",
             "--rule", rule, BAD],
            capture_output=True, text=True, timeout=120, cwd=root,
            env=env)
        assert res.returncode == 1, (rule, res.stdout, res.stderr)
        assert f" {rule} " in res.stdout, (rule, res.stdout)
    good = subprocess.run(
        [sys.executable, "-m", "megba_tpu.analysis.lint",
         "--rule", "stale-program", "--rule", "cache-split",
         "--rule", "key-surface-drift", GOOD],
        capture_output=True, text=True, timeout=120, cwd=root, env=env)
    assert good.returncode == 0, (good.stdout, good.stderr)


def test_suppression_comment_respected(tmp_path):
    """The framework-wide `# megba: allow-<rule>` hatch applies to the
    identity rules like any other."""
    findings = _src_lint(tmp_path, """\

        def flat_solve(residual_jac_fn, x, option: ProblemOption):
            sink = option.telemetry  # megba: allow-stale-program
            return x, sink
        """, rules=["stale-program"])
    assert findings == []
