"""Utils tests: debug helpers, timers, checkpoint round-trip."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.utils import (
    PhaseTimer,
    assert_all_finite,
    describe_array,
    load_state,
    print_blocks,
    save_state,
    trace_profile,
)


def test_describe_array():
    s = describe_array("x", np.array([1.0, 2.0, np.inf]))
    assert "NONFINITE=1" in s and "shape=(3,)" in s
    assert "empty" in describe_array("e", np.zeros((0, 3)))


def test_assert_all_finite():
    assert_all_finite(jnp.ones(3), "ok")
    with pytest.raises(FloatingPointError, match="bad"):
        assert_all_finite(jnp.asarray([1.0, np.nan]), "bad")


def test_assert_all_finite_under_jit():
    import jax

    @jax.jit
    def f(x):
        return assert_all_finite(x * 2, "traced")

    np.testing.assert_allclose(f(jnp.ones(3)), 2.0)


def test_assert_all_finite_debug_raises_in_jit():
    import jax

    @jax.jit
    def f(x):
        return assert_all_finite(x / x, "traced-debug", debug=True)

    np.testing.assert_allclose(f(jnp.ones(3)), 1.0)  # clean: silent
    with pytest.raises((FloatingPointError, Exception)):
        jax.block_until_ready(f(jnp.zeros(3)))  # 0/0 -> NaN -> raise


def test_print_blocks(capsys):
    print_blocks("Hpp", np.eye(3)[None].repeat(4, 0))
    out = capsys.readouterr().out
    assert "4 blocks of 3x3" in out


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b") as ph:
        out = ph.sync(jnp.ones(2) * 2)  # produced INSIDE the block
    np.testing.assert_allclose(out, 2.0)
    assert t.counts["a"] == 2 and t.counts["b"] == 1
    assert "a:" in t.report()


def test_phase_timer_as_dict_reset_and_totals():
    t = PhaseTimer()
    assert t.report() == "no phases recorded"  # sensible empty report
    assert t.as_dict() == {}
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    d = t.as_dict()
    assert d["a"]["calls"] == 2 and d["a"]["total_s"] >= 0.0
    import json

    json.dumps(d)  # the SolveReport `phases` payload must be plain JSON
    assert "total:" in t.report()  # total line present
    t.reset()
    assert t.as_dict() == {} and t.report() == "no phases recorded"


def test_trace_profile_noop():
    with trace_profile(None):
        pass


def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ck.npz")
    save_state(p, np.ones((2, 9)), np.zeros((3, 3)), region=10.0, cost=5.5,
               iteration=7, extra={"v": np.arange(3)})
    got = load_state(p)
    np.testing.assert_array_equal(got["cameras"], np.ones((2, 9)))
    np.testing.assert_array_equal(got["points"], np.zeros((3, 3)))
    assert float(got["region"]) == 10.0 and int(got["iteration"]) == 7
    np.testing.assert_array_equal(got["extra_v"], np.arange(3))
    # Overwrite is atomic (no stray tmp files).
    save_state(p, np.zeros((2, 9)), np.ones((3, 3)))
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


def test_parse_verbose_curve_matches_emit_format():
    """The curve parser must track algo/lm.py's emit format — a drift
    raises instead of silently returning empty curves (the committed
    evidence artifacts depend on this)."""
    import pytest

    from megba_tpu.utils.curves import parse_verbose_curve

    text = (
        "iter 0: cost 1.234560e+05 log10 5.092 accept True pcg_iters 12 "
        "elapsed 103.2 ms\n"
        "iter 1: cost 9.900000e+03 log10 3.996 accept False pcg_iters 7 "
        "elapsed 201.9 ms\n")
    curve = parse_verbose_curve(text)
    assert curve == [
        {"iter": 0, "cost": 123456.0, "accept": True, "pcg_iters": 12},
        {"iter": 1, "cost": 9900.0, "accept": False, "pcg_iters": 7},
    ]
    with pytest.raises(ValueError, match="verbose format"):
        parse_verbose_curve("no lines here")
    assert parse_verbose_curve("", require=False) == []


def test_run_with_curve_captures_real_solver_lines():
    """End-to-end: a real verbose solve through run_with_curve yields a
    non-empty curve whose first entry is iteration 0."""
    import numpy as np

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.common import JacobianMode
    from megba_tpu.solve import flat_solve
    from megba_tpu.utils.curves import run_with_curve

    s = make_synthetic_bal(num_cameras=4, num_points=40, obs_per_point=4,
                           seed=0, dtype=np.float64)
    option = ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=3),
        solver_option=SolverOption(max_iter=8, tol=1e-10))
    f = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    res, curve = run_with_curve(lambda: flat_solve(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
        verbose=True))
    assert curve and curve[0]["iter"] == 0
    assert curve[-1]["cost"] <= curve[0]["cost"] * 1.0000001
    assert len(curve) == int(res.iterations)


def test_compile_cache_dir_resolution(tmp_path, monkeypatch):
    """enable_persistent_compile_cache resolves the cache dir with the
    documented precedence: explicit arg > MEGBA_COMPILE_CACHE_DIR >
    JAX_COMPILATION_CACHE_DIR > repo-local .jax_cache."""
    import jax

    from megba_tpu.utils.backend import enable_persistent_compile_cache

    orig = jax.config.jax_compilation_cache_dir
    try:
        explicit = tmp_path / "explicit"
        assert enable_persistent_compile_cache(str(explicit)) == str(explicit)
        assert explicit.is_dir()

        monkeypatch.setenv("MEGBA_COMPILE_CACHE_DIR",
                           str(tmp_path / "megba_env"))
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR",
                           str(tmp_path / "jax_env"))
        assert enable_persistent_compile_cache().endswith("megba_env")

        monkeypatch.delenv("MEGBA_COMPILE_CACHE_DIR")
        assert enable_persistent_compile_cache().endswith("jax_env")

        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
        assert enable_persistent_compile_cache().endswith(".jax_cache")
    finally:
        jax.config.update("jax_compilation_cache_dir", orig)
