"""Utils tests: debug helpers, timers, checkpoint round-trip."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.utils import (
    PhaseTimer,
    assert_all_finite,
    describe_array,
    load_state,
    print_blocks,
    save_state,
    trace_profile,
)


def test_describe_array():
    s = describe_array("x", np.array([1.0, 2.0, np.inf]))
    assert "NONFINITE=1" in s and "shape=(3,)" in s
    assert "empty" in describe_array("e", np.zeros((0, 3)))


def test_assert_all_finite():
    assert_all_finite(jnp.ones(3), "ok")
    with pytest.raises(FloatingPointError, match="bad"):
        assert_all_finite(jnp.asarray([1.0, np.nan]), "bad")


def test_assert_all_finite_under_jit():
    import jax

    @jax.jit
    def f(x):
        return assert_all_finite(x * 2, "traced")

    np.testing.assert_allclose(f(jnp.ones(3)), 2.0)


def test_assert_all_finite_debug_raises_in_jit():
    import jax

    @jax.jit
    def f(x):
        return assert_all_finite(x / x, "traced-debug", debug=True)

    np.testing.assert_allclose(f(jnp.ones(3)), 1.0)  # clean: silent
    with pytest.raises((FloatingPointError, Exception)):
        jax.block_until_ready(f(jnp.zeros(3)))  # 0/0 -> NaN -> raise


def test_print_blocks(capsys):
    print_blocks("Hpp", np.eye(3)[None].repeat(4, 0))
    out = capsys.readouterr().out
    assert "4 blocks of 3x3" in out


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b") as ph:
        out = ph.sync(jnp.ones(2) * 2)  # produced INSIDE the block
    np.testing.assert_allclose(out, 2.0)
    assert t.counts["a"] == 2 and t.counts["b"] == 1
    assert "a:" in t.report()


def test_trace_profile_noop():
    with trace_profile(None):
        pass


def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ck.npz")
    save_state(p, np.ones((2, 9)), np.zeros((3, 3)), region=10.0, cost=5.5,
               iteration=7, extra={"v": np.arange(3)})
    got = load_state(p)
    np.testing.assert_array_equal(got["cameras"], np.ones((2, 9)))
    np.testing.assert_array_equal(got["points"], np.zeros((3, 3)))
    assert float(got["region"]) == 10.0 and int(got["iteration"]) == 7
    np.testing.assert_array_equal(got["extra_v"], np.arange(3))
    # Overwrite is atomic (no stray tmp files).
    save_state(p, np.zeros((2, 9)), np.ones((3, 3)))
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []
