"""Transport-layer tests: frame codec hardening, handshake, policies.

All compile-free tier-1: the frame format and its typed failure
taxonomy (magic / length-bomb / digest / truncation), partial-read and
slow-writer delivery, pipe↔TCP byte equivalence, the register/ack
token handshake, `ReconnectPolicy` determinism, and the worker-side
`DedupCache` idempotence seam.
"""

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from megba_tpu.serving.transport import (
    HEADER_SIZE,
    MAGIC,
    PROTOCOL_VERSION,
    DedupCache,
    FrameDigestError,
    FrameError,
    FrameLengthError,
    FrameMagicError,
    FrameTruncatedError,
    HandshakeError,
    PipeTransport,
    ReconnectPolicy,
    TcpTransport,
    ack_frame,
    decode_frame,
    encode_frame,
    heartbeat_frame,
    is_heartbeat,
    parse_address,
    refusal_frame,
    register_frame,
    verify_ack,
    verify_register,
)

ENV = {"jax": "0.9", "jaxlib": "0.9", "backend": "cpu"}


def _tcp_pair():
    a, b = socket.socketpair()
    return TcpTransport(a), TcpTransport(b)


def _pipe():
    r, w = os.pipe()
    return PipeTransport(os.fdopen(r, "rb", buffering=0),
                         os.fdopen(w, "wb", buffering=0))


# ---------------------------------------------------------------------------
# Frame codec
# ---------------------------------------------------------------------------


def test_encode_decode_roundtrip_including_arrays():
    msg = {"op": "solve", "x": np.arange(1000.0).reshape(10, 100),
           "nested": [1, "two", {"three": np.int32(3)}]}
    out = decode_frame(encode_frame(msg))
    np.testing.assert_array_equal(out["x"], msg["x"])
    assert out["nested"][2]["three"] == 3


def test_frame_layout_is_magic_length_digest():
    frame = encode_frame({"a": 1})
    assert frame[:4] == MAGIC
    (length,) = struct.unpack(">Q", frame[4:12])
    assert length == len(frame) - HEADER_SIZE


def test_bad_magic_is_typed_and_names_observed_bytes():
    frame = bytearray(encode_frame({"a": 1}))
    frame[:4] = b"HTTP"
    with pytest.raises(FrameMagicError, match="HTTP"):
        decode_frame(bytes(frame))


def test_oversize_length_bomb_rejected_before_allocation():
    # A corrupted length field must be refused from the HEADER alone —
    # never used as an allocation size.
    header = MAGIC + struct.pack(">Q", 1 << 60) + b"\0" * 16
    with pytest.raises(FrameLengthError, match="1152921504606846976"):
        decode_frame(header)


def test_payload_corruption_is_typed_digest_mismatch():
    frame = bytearray(encode_frame({"a": 1}))
    frame[-1] ^= 0xFF
    with pytest.raises(FrameDigestError):
        decode_frame(bytes(frame))


def test_truncated_payload_names_got_and_need():
    frame = encode_frame({"payload": b"x" * 1000})
    with pytest.raises(FrameTruncatedError) as ei:
        decode_frame(frame[:HEADER_SIZE + 10])
    assert ei.value.got == 10
    assert ei.value.need == len(frame) - HEADER_SIZE


def test_pipe_and_tcp_ship_identical_bytes():
    # The carrier contract: both transports ship exactly encode_frame's
    # bytes, so a frame written by one is readable by the other.
    msg = {"op": "solve", "x": np.arange(32.0)}
    wire = encode_frame(msg)
    r, w = os.pipe()
    chan = PipeTransport(os.fdopen(r, "rb", buffering=0),
                         os.fdopen(w, "wb", buffering=0))
    chan.send(msg)
    assert os.read(r, 1 << 20) == wire
    chan.close()
    a, b = socket.socketpair()
    ta = TcpTransport(a)
    ta.send(msg)
    got = b.recv(1 << 20)
    assert got == wire
    ta.close()
    b.close()


def test_tcp_partial_reads_slow_writer_delivers_whole_frame():
    # Dribble the frame a few bytes at a time from a slow writer
    # thread: recv must assemble it across many partial reads.
    ta, tb = _tcp_pair()
    msg = {"x": np.arange(256.0), "s": "slow"}
    wire = encode_frame(msg)

    def dribble():
        for i in range(0, len(wire), 7):
            ta._sock.sendall(wire[i:i + 7])
            time.sleep(0.001)

    t = threading.Thread(target=dribble)
    t.start()
    out = tb.recv(timeout_s=30.0)
    t.join()
    np.testing.assert_array_equal(out["x"], msg["x"])
    ta.close()
    tb.close()


def test_tcp_mid_frame_eof_is_typed_truncation():
    ta, tb = _tcp_pair()
    wire = encode_frame({"payload": b"y" * 4096})
    ta._sock.sendall(wire[:HEADER_SIZE + 100])
    ta.close()
    with pytest.raises(FrameTruncatedError) as ei:
        tb.recv(timeout_s=5.0)
    assert ei.value.got < ei.value.need
    tb.close()


def test_tcp_mid_header_eof_is_typed_truncation():
    ta, tb = _tcp_pair()
    ta._sock.sendall(encode_frame({"a": 1})[:HEADER_SIZE - 5])
    ta.close()
    with pytest.raises(FrameTruncatedError, match="header"):
        tb.recv(timeout_s=5.0)
    tb.close()


def test_tcp_recv_timeout_and_poll_abort():
    ta, tb = _tcp_pair()
    with pytest.raises(TimeoutError):
        tb.recv(timeout_s=0.15)

    class Boom(RuntimeError):
        pass

    def poll():
        raise Boom("dead")

    with pytest.raises(Boom):
        tb.recv(timeout_s=5.0, poll=poll)
    ta.close()
    tb.close()


def test_desync_garbage_prefix_is_magic_error():
    # A peer speaking another protocol (or a reordered stream) fails
    # typed on the first header, not with an unpickling crash.
    ta, tb = _tcp_pair()
    ta._sock.sendall(b"\x00" * HEADER_SIZE)
    with pytest.raises(FrameMagicError):
        tb.recv(timeout_s=5.0)
    ta.close()
    tb.close()


# ---------------------------------------------------------------------------
# Handshake
# ---------------------------------------------------------------------------


def test_register_verify_roundtrip():
    reg = register_frame("w3", "tok", 2, 123, ENV)
    assert verify_register(reg, "tok", ENV) == "w3"


def test_register_token_refused_before_anything_else():
    # Wrong token must be the FIRST refusal even when other fields
    # drift too — an unauthenticated peer learns nothing else.
    reg = register_frame("w0", "bad", 0, 1, {"jax": "drifted"})
    with pytest.raises(HandshakeError) as ei:
        verify_register(reg, "tok", ENV)
    assert ei.value.field == "token"


def test_register_protocol_drift_typed():
    reg = dict(register_frame("w0", "tok", 0, 1, ENV), protocol=1)
    # The MAC covers the protocol string, so a tampered protocol fails
    # as either token or protocol — both typed.
    with pytest.raises(HandshakeError):
        verify_register(reg, "tok", ENV)


def test_register_env_fingerprint_drift_names_field():
    drifted = dict(ENV, jaxlib="9.9-other")
    reg = register_frame("w0", "tok", 0, 1, drifted)
    with pytest.raises(HandshakeError) as ei:
        verify_register(reg, "tok", ENV)
    assert ei.value.field == "env:jaxlib"
    assert "9.9-other" in str(ei.value)


def test_ack_verify_and_refusal_roundtrip():
    ack = ack_frame("resume", "tok", "w0")
    assert verify_ack(ack, "tok", "w0") == "resume"
    with pytest.raises(HandshakeError):
        verify_ack(ack, "other", "w0")  # router must prove the token too
    with pytest.raises(HandshakeError) as ei:
        verify_ack(refusal_frame(HandshakeError("protocol", 1,
                                                PROTOCOL_VERSION)),
                   "tok", "w0")
    assert ei.value.field == "protocol"


def test_mac_binds_worker_identity():
    # w0's register MAC replayed under w1's id must not verify.
    reg = dict(register_frame("w0", "tok", 0, 1, ENV), worker_id="w1")
    with pytest.raises(HandshakeError) as ei:
        verify_register(reg, "tok", ENV)
    assert ei.value.field == "token"


# ---------------------------------------------------------------------------
# Policies and helpers
# ---------------------------------------------------------------------------


def test_reconnect_policy_deterministic_capped_and_jittered():
    p = ReconnectPolicy(base_s=0.05, factor=2.0, cap_s=2.0, jitter=0.5,
                        seed=3)
    series = [p.backoff_s(7, a) for a in range(1, 9)]
    assert series == [p.backoff_s(7, a) for a in range(1, 9)]  # replay
    assert series != [p.backoff_s(8, a) for a in range(1, 9)]  # per-key
    for a, s in enumerate(series, start=1):
        base = min(0.05 * 2.0 ** (a - 1), 2.0)
        assert base * 0.5 <= s <= base * 1.5
    with pytest.raises(ValueError):
        ReconnectPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        ReconnectPolicy(jitter=1.5)


def test_parse_address():
    assert parse_address("127.0.0.1:80") == ("127.0.0.1", 80)
    assert parse_address("[::1]:8080") == ("::1", 8080)
    with pytest.raises(ValueError):
        parse_address("no-port")
    with pytest.raises(ValueError):
        parse_address("host:notanint")


def test_heartbeat_frames_are_skimmable():
    assert is_heartbeat(heartbeat_frame(3, "w0"))
    assert not is_heartbeat({"op": "solve"})
    assert not is_heartbeat("not-a-dict")


def test_dedup_cache_bounded_fifo_and_hit_count():
    d = DedupCache(capacity=3)
    for seq in range(5):
        assert d.get(seq) is None  # miss before put
        d.put(seq, {"seq": seq})
    assert d.get(0) is None and d.get(1) is None  # evicted, FIFO
    assert d.get(4) == {"seq": 4}
    assert d.get(3) == {"seq": 3}
    assert d.hit_count() == 2
