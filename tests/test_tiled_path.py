"""End-to-end equivalence of the tiled (scatter-free) path vs the classic
scatter-add path.

The tiled path reorders edges (dual plans), runs the fused build kernel
and tiled coupling products; results must agree with the plain path up
to f32 summation order.  Kernels are additionally exercised in Pallas
interpret mode (the real-Mosaic check lives in tests/test_tpu.py).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megba_tpu.common import (
    AlgoOption,
    ComputeKind,
    PreconditionerKind,
    ProblemOption,
    SolverOption,
)
from megba_tpu.algo.lm import lm_solve
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.linear_system.builder import build_schur_system, weight_system_inputs
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.ops.segtiles import make_dual_plans
from megba_tpu.solve import flat_solve


def _problem(seed=0, num_cameras=14, num_points=200, obs_per_point=4):
    return make_synthetic_bal(
        num_cameras=num_cameras, num_points=num_points,
        obs_per_point=obs_per_point, seed=seed, param_noise=3e-2,
        pixel_noise=0.4, dtype=np.float32)


def _option(compute, mixed=False, precond=PreconditionerKind.HPP):
    return ProblemOption(
        dtype=np.float32,
        compute_kind=compute,
        mixed_precision_pcg=mixed,
        algo_option=AlgoOption(max_iter=6, epsilon1=1e-10, epsilon2=1e-14),
        solver_option=SolverOption(
            max_iter=40, tol=1e-8, refuse_ratio=1e30, preconditioner=precond),
    )


@pytest.mark.parametrize("compute", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_flat_solve_tiled_matches_plain(compute):
    s = _problem()
    f = make_residual_jacobian_fn()
    opt = _option(compute)
    plain = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                       opt, use_tiled=False)
    tiled = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                       opt, use_tiled=True)
    assert int(tiled.iterations) == int(plain.iterations)
    assert int(tiled.accepted) == int(plain.accepted)
    np.testing.assert_allclose(
        float(tiled.initial_cost), float(plain.initial_cost), rtol=1e-5)
    np.testing.assert_allclose(
        float(tiled.cost), float(plain.cost), rtol=1e-4)
    # No raw-parameter assertion, same rationale as
    # test_sharded_tiled_matches_single: the tiled path reduces in plan
    # slot order, the plain path in edge order, and over 6 accept-all LM
    # iterations the f32 rounding difference walks the weakly-determined
    # camera components (distortion k1/k2, small rotation entries) within
    # the gauge-free basin — while iterations, accepts and costs stay in
    # lockstep.  No fixed band survives that walk: XLA:CPU fresh compiles
    # are not run-to-run deterministic in summation order, and the same
    # seed has been observed to land anywhere from ~5e-3 to a different
    # gauge-equivalent point entirely (half the entries moved, cost still
    # matching to 1e-4).  The cost trajectory is the equivalence check.


def test_tiled_build_matches_plain_build():
    s = _problem(seed=3)
    f = make_residual_jacobian_fn()
    nc, npts = s.cameras0.shape[0], s.points0.shape[0]
    plan_c, plans = make_dual_plans(
        s.cam_idx, s.pt_idx, nc, npts, use_kernels=False)

    cams = jnp.asarray(s.cameras0.T.astype(np.float32))
    pts = jnp.asarray(s.points0.T.astype(np.float32))

    # Plain (unsorted, no padding) reference build.
    obs_fm = jnp.asarray(s.obs.T.astype(np.float32))
    ci = jnp.asarray(s.cam_idx)
    pi = jnp.asarray(s.pt_idx)
    r, Jc, Jp = f(jnp.take(cams, ci, axis=1), jnp.take(pts, pi, axis=1),
                  obs_fm)
    mask1 = jnp.ones(s.cam_idx.shape[0], jnp.float32)
    r, Jc, Jp = weight_system_inputs(r, Jc, Jp, ci, pi, mask1)
    ref = build_schur_system(r, Jc, Jp, ci, pi, nc, npts)

    # Tiled build in plan slot order.
    perm, pmask = plan_c.perm, plan_c.mask
    obs_p = jnp.asarray((s.obs[perm] * pmask[:, None]).T.astype(np.float32))
    ci_p = jnp.asarray(plan_c.seg)
    pi_p = jnp.asarray(np.where(pmask > 0, s.pt_idx[perm], 0))
    r2, Jc2, Jp2 = f(jnp.take(cams, ci_p, axis=1),
                     jnp.take(pts, pi_p, axis=1), obs_p)
    r2, Jc2, Jp2 = weight_system_inputs(
        r2, Jc2, Jp2, ci_p, pi_p, jnp.asarray(pmask))
    Jp2_pt = plans.to_pt(Jp2)

    for uk, interp in ((False, False), (False, True)):
        p = dataclasses.replace(plans, use_kernels=uk)
        if interp:
            from megba_tpu.ops.segtiles import jtj_grad_reduce

            hpp_rows, g_cam = jtj_grad_reduce(
                Jc2, r2, p.cam, use_kernels=False, interpret=True)
            hll, g_pt = jtj_grad_reduce(
                Jp2_pt, p.to_pt(r2), p.pt, use_kernels=False, interpret=True)
            got = dict(hpp_rows=hpp_rows, g_cam=g_cam, hll=hll, g_pt=g_pt)
            cd = 9
            Hpp = jnp.moveaxis(hpp_rows.reshape(cd, cd, nc), -1, 0)
            np.testing.assert_allclose(
                np.asarray(Hpp), np.asarray(ref.Hpp), rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(
                np.asarray(got["hll"]), np.asarray(ref.Hll),
                rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(
                np.asarray(got["g_pt"]), np.asarray(ref.g_pt),
                rtol=2e-4, atol=2e-4)
        else:
            sys2 = build_schur_system(
                r2, Jc2, Jp2_pt, ci_p, pi_p, nc, npts, plans=p)
            np.testing.assert_allclose(
                np.asarray(sys2.Hpp), np.asarray(ref.Hpp),
                rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(
                np.asarray(sys2.Hll), np.asarray(ref.Hll),
                rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(
                np.asarray(sys2.g_cam), np.asarray(ref.g_cam),
                rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(
                np.asarray(sys2.g_pt), np.asarray(ref.g_pt),
                rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("ws", [2, 8])
def test_sharded_tiled_matches_single(ws):
    # The per-shard tiled path over the CPU mesh must agree with the
    # single-device tiled solve (SURVEY.md §2.3: replicate + psum).
    s = _problem(seed=21, num_cameras=10, num_points=150, obs_per_point=5)
    f = make_residual_jacobian_fn()
    opt1 = _option(ComputeKind.IMPLICIT)
    single = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                        s.pt_idx, opt1, use_tiled=True)
    optw = dataclasses.replace(opt1, world_size=ws)
    sharded = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                         s.pt_idx, optw, use_tiled=True)
    assert int(sharded.iterations) == int(single.iterations)
    # Per-shard plans change f32 summation order, which can flip a
    # marginal accept/reject and let parameters drift within the basin
    # (BA is also gauge-free), so the equivalence assertion is on the
    # achieved cost, not on raw parameters.
    np.testing.assert_allclose(
        float(sharded.initial_cost), float(single.initial_cost), rtol=1e-5)
    np.testing.assert_allclose(
        float(sharded.cost), float(single.cost), rtol=1e-4)


def test_sharded_plan_invariants():
    # Each shard's plan covers all segments; real edges are exactly
    # partitioned across shards.
    from megba_tpu.ops.segtiles import make_sharded_dual_plans

    rng = np.random.default_rng(2)
    n, nc, npts, ws = 5000, 23, 400, 4
    cam = np.sort(rng.integers(0, nc, n)).astype(np.int32)
    pt = rng.integers(0, npts, n).astype(np.int32)
    perms, masks, cam_segs, plans = make_sharded_dual_plans(
        cam, pt, nc, npts, ws, use_kernels=False)
    assert perms.shape[0] == ws and masks.shape == perms.shape
    seen = np.concatenate(
        [perms[k][masks[k] > 0] for k in range(ws)])
    assert np.array_equal(np.sort(seen), np.arange(n))
    # Stacked leaves share shapes across shards.
    assert plans.cam.tile_block.shape[0] == ws
    assert plans.pt.tile_block.shape[0] == ws
    # The per-shard cam stream is non-decreasing (the sorted-scatter
    # promise), in range, and matches the real edges' cameras.
    assert cam_segs.shape == masks.shape
    for k in range(ws):
        assert np.all(np.diff(cam_segs[k]) >= 0)
        assert cam_segs[k].min() >= 0 and cam_segs[k].max() < nc
        np.testing.assert_array_equal(
            cam_segs[k][masks[k] > 0], cam[perms[k][masks[k] > 0]])


def test_sharded_uneven_shards_stack():
    # Shard sizes differing by one edge must still produce stackable
    # plans (tile sizes are fitted once from the largest shard).
    from megba_tpu.ops.segtiles import make_sharded_dual_plans

    rng = np.random.default_rng(3)
    for n in (1025, 513, 127):  # odd sizes -> uneven 2-way splits
        nc, npts, ws = 7, 50, 2
        cam = np.sort(rng.integers(0, nc, n)).astype(np.int32)
        pt = rng.integers(0, npts, n).astype(np.int32)
        perms, masks, cam_segs, plans = make_sharded_dual_plans(
            cam, pt, nc, npts, ws, use_kernels=False)
        assert plans.cam.mask.shape[0] == ws  # stacked, not raised


@pytest.mark.slow
def test_sharded_tiled_realistic_scale():
    # The sharded tiled path at non-degenerate plan sizes: ≥500k edges,
    # world 8, thousands of tiles with multiple tiles per block — the
    # junk-block padding, cross-shard psum alignment, and per-shard
    # tile-count equalisation all exercised at realistic (not toy)
    # shapes.  Cost parity with the single-device tiled solve is the
    # invariant (parameters are gauge-free; see
    # test_sharded_tiled_matches_single).
    s = make_synthetic_bal(
        num_cameras=120, num_points=100_000, obs_per_point=5.2,
        seed=31, param_noise=2e-2, pixel_noise=0.4, dtype=np.float32)
    assert s.obs.shape[0] >= 500_000
    f = make_residual_jacobian_fn()
    opt1 = ProblemOption(
        dtype=np.float32,
        compute_kind=ComputeKind.IMPLICIT,
        algo_option=AlgoOption(max_iter=2, epsilon1=1e-10, epsilon2=1e-14),
        solver_option=SolverOption(
            max_iter=8, tol=1e-8, refuse_ratio=1e30),
    )
    single = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                        s.pt_idx, opt1, use_tiled=True)
    optw = dataclasses.replace(opt1, world_size=8)
    sharded = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                         s.pt_idx, optw, use_tiled=True)
    assert int(sharded.iterations) == int(single.iterations)
    np.testing.assert_allclose(
        float(sharded.initial_cost), float(single.initial_cost), rtol=1e-5)
    np.testing.assert_allclose(
        float(sharded.cost), float(single.cost), rtol=1e-4)


def test_tiled_mixed_precision_converges():
    s = _problem(seed=5)
    f = make_residual_jacobian_fn()
    opt = _option(ComputeKind.IMPLICIT, mixed=True)
    res = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                     opt, use_tiled=True)
    assert float(res.cost) < 0.1 * float(res.initial_cost)


def test_tiled_schur_diag_preconditioner():
    s = _problem(seed=6)
    f = make_residual_jacobian_fn()
    opt = _option(ComputeKind.IMPLICIT,
                  precond=PreconditionerKind.SCHUR_DIAG)
    res = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                     opt, use_tiled=True)
    assert float(res.cost) < 0.1 * float(res.initial_cost)


def test_tiled_robust_loss():
    from megba_tpu.ops.robust import RobustKind

    s = _problem(seed=7)
    f = make_residual_jacobian_fn()
    opt = dataclasses.replace(
        _option(ComputeKind.IMPLICIT), robust_kind=RobustKind.HUBER,
        robust_delta=2.0)
    plain = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                       opt, use_tiled=False)
    tiled = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                       opt, use_tiled=True)
    np.testing.assert_allclose(
        float(tiled.cost), float(plain.cost), rtol=1e-3)
