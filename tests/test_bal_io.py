"""BAL file format round-trip + validation tests."""

import numpy as np
import pytest

from megba_tpu.io.bal import BALFile, load_bal, loads_bal, save_bal
from megba_tpu.io.synthetic import make_synthetic_bal


def synthetic_file():
    s = make_synthetic_bal(num_cameras=3, num_points=10, obs_per_point=2, seed=5)
    return BALFile(cameras=s.cameras0, points=s.points0, obs=s.obs,
                   cam_idx=s.cam_idx, pt_idx=s.pt_idx)


def test_roundtrip(tmp_path):
    bal = synthetic_file()
    p = tmp_path / "problem.txt"
    save_bal(p, bal)
    got = load_bal(p)
    np.testing.assert_array_equal(got.cam_idx, bal.cam_idx)
    np.testing.assert_array_equal(got.pt_idx, bal.pt_idx)
    np.testing.assert_allclose(got.obs, bal.obs, rtol=0)
    np.testing.assert_allclose(got.cameras, bal.cameras, rtol=0)
    np.testing.assert_allclose(got.points, bal.points, rtol=0)


def test_parse_reference_layout():
    # Hand-built tiny file in the exact BAL layout.
    text = """2 2 3
0 0 1.5 -2.5
0 1 0.25 0.75
1 1 -1.0 3.0
""" + "\n".join(str(float(i)) for i in range(18)) + "\n" + "\n".join(
        str(float(i)) for i in range(6))
    bal = loads_bal(text)
    assert bal.num_cameras == 2 and bal.num_points == 2 and bal.num_observations == 3
    np.testing.assert_array_equal(bal.cam_idx, [0, 0, 1])
    np.testing.assert_array_equal(bal.pt_idx, [0, 1, 1])
    np.testing.assert_allclose(bal.obs[0], [1.5, -2.5])
    np.testing.assert_allclose(bal.cameras[1], np.arange(9.0) + 9)
    np.testing.assert_allclose(bal.points[0], [0.0, 1.0, 2.0])


def test_solve_bal_one_call(tmp_path):
    from megba_tpu import ProblemOption, solve_bal
    from megba_tpu.common import AlgoOption, JacobianMode, SolverOption

    s = make_synthetic_bal(num_cameras=5, num_points=30, obs_per_point=3,
                           seed=12, param_noise=3e-2, pixel_noise=0.2)
    # Scramble the edge order to exercise the native sort path.
    perm = np.random.default_rng(0).permutation(len(s.obs))
    bal = BALFile(cameras=s.cameras0, points=s.points0, obs=s.obs[perm],
                  cam_idx=s.cam_idx[perm], pt_idx=s.pt_idx[perm])
    p = tmp_path / "p.txt"
    save_bal(p, bal)
    option = ProblemOption(
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=15, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-10, tol_relative=True,
                                   refuse_ratio=1e30))
    # Independently held copies: solve_bal must return the ORIGINAL
    # (scrambled) order, not its internal camera-sorted permutation.
    cam_idx_before = bal.cam_idx.copy()
    obs_before = bal.obs.copy()
    solved, result = solve_bal(str(p), option)
    assert float(result.cost) < float(result.initial_cost) * 1e-2
    np.testing.assert_array_equal(solved.cam_idx, cam_idx_before)
    np.testing.assert_array_equal(solved.obs, obs_before)
    assert not np.allclose(solved.cameras, s.cameras0)


def test_truncated_file_raises():
    with pytest.raises(ValueError, match="token count"):
        loads_bal("2 2 3\n0 0 1.0 2.0\n")


def test_bad_indices_raise():
    text = "1 1 1\n0 5 1.0 2.0\n" + "\n".join(["0.0"] * 12)
    with pytest.raises(ValueError, match="out of range"):
        loads_bal(text)
