"""Fault containment and recovery (megba_tpu/robustness/ + RobustOption).

Contract under test, in three layers:

- **Guards are free**: with `RobustOption(guards=True)` and nothing
  failing, the solve is BITWISE identical to the unguarded one (every
  guard is a select whose taken branch is the clean value).
- **Guards contain seeded faults**: a NaN residual burst and a
  Schur-indefiniteness burst each recover on-device
  (status=RECOVERED, final cost at the clean run's), while the same
  injection with guards off demonstrably poisons or degrades the solve
  — proving the guard, not luck, did the work.
- **Termination semantics**: LMResult.status partitions
  converged / max_iter / stalled / recovered / fatal_nonfinite, on
  device, consistently with the stop flag and accept counts.

One problem/config pair is shared across the module (compile-cache
friendly: each distinct program lowers once).
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from megba_tpu.common import (
    AlgoOption,
    JacobianMode,
    ProblemOption,
    PreconditionerKind,
    RobustOption,
    SolverOption,
    SolveStatus,
    status_name,
    validate_options,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.robustness.faults import (
    FaultPlan,
    lower_edge_vector,
    make_nan_burst,
    make_point_indefinite_burst,
    with_offset,
)
from megba_tpu.solve import flat_solve


@pytest.fixture(scope="module")
def problem():
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=1, param_noise=4e-2, pixel_noise=0.3)
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=12, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-13,
                                   refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    return s, option, f


def _args(s, f):
    return (f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx)


def _guarded(option, **kw):
    return dataclasses.replace(
        option, robust_option=RobustOption(guards=True, **kw))


@pytest.fixture(scope="module")
def clean_off(problem):
    s, option, f = problem
    return flat_solve(*_args(s, f), option)


@pytest.fixture(scope="module")
def clean_on(problem):
    s, option, f = problem
    return flat_solve(*_args(s, f), _guarded(option))


@pytest.fixture(scope="module")
def nan_plan(problem):
    s, _, _ = problem
    # Burst covering iteration 0: poisons the initial linearisation too,
    # so the guard-off baseline carries a non-finite cost forever.
    return make_nan_burst(s.obs.shape[0], [2, 9], start=0, stop=1)


# ------------------------------------------------------------------ free


def test_clean_run_bitwise_unchanged_with_guards(clean_off, clean_on):
    assert np.array_equal(np.asarray(clean_off.cameras),
                          np.asarray(clean_on.cameras))
    assert np.array_equal(np.asarray(clean_off.points),
                          np.asarray(clean_on.points))
    assert (np.asarray(clean_off.cost).tobytes()
            == np.asarray(clean_on.cost).tobytes())
    assert int(clean_on.recoveries) == 0
    assert int(clean_off.status) == int(clean_on.status)
    # And the guarded trace recorded no fault events.
    it = int(clean_on.iterations)
    assert not np.asarray(clean_on.trace.recovery)[:it].any()
    assert not np.asarray(clean_on.trace.pcg_breakdown)[:it].any()


# ------------------------------------------------------- NaN residual burst


def test_nan_burst_poisons_unguarded_solve(problem, nan_plan):
    s, option, f = problem
    res = flat_solve(*_args(s, f), option, fault_plan=nan_plan)
    assert not np.isfinite(float(res.cost))
    # Nothing was ever accepted against a NaN carried cost: stalled.
    assert int(res.status) == SolveStatus.STALLED
    assert int(res.accepted) == 0


def test_nan_burst_recovers_with_guards(problem, nan_plan, clean_off):
    s, option, f = problem
    res = flat_solve(*_args(s, f), _guarded(option), fault_plan=nan_plan)
    assert int(res.status) == SolveStatus.RECOVERED
    assert int(res.recoveries) >= 1
    assert np.isfinite(float(res.cost))
    np.testing.assert_allclose(float(res.cost), float(clean_off.cost),
                               rtol=1e-4)
    it = int(res.iterations)
    rec = np.asarray(res.trace.recovery)[:it]
    assert rec[:2].any() and not rec[2:].any()


def test_nan_burst_world2_matches_single_device(problem, nan_plan):
    s, option, f = problem
    single = flat_solve(*_args(s, f), _guarded(option), fault_plan=nan_plan)
    w2 = flat_solve(*_args(s, f),
                    dataclasses.replace(_guarded(option), world_size=2),
                    fault_plan=nan_plan)
    assert int(w2.status) == SolveStatus.RECOVERED
    assert int(w2.recoveries) == int(single.recoveries)
    np.testing.assert_allclose(float(w2.cost), float(single.cost),
                               rtol=1e-10)


def test_fault_injection_is_deterministic(problem, nan_plan):
    s, option, f = problem
    a = flat_solve(*_args(s, f), _guarded(option), fault_plan=nan_plan)
    b = flat_solve(*_args(s, f), _guarded(option), fault_plan=nan_plan)
    assert np.array_equal(np.asarray(a.cameras), np.asarray(b.cameras))
    assert np.array_equal(np.asarray(a.points), np.asarray(b.points))
    assert float(a.cost) == float(b.cost)


def test_fatal_after_max_recoveries(problem):
    s, option, f = problem
    # Persistent fault: every recovery relinearisation is poisoned too,
    # so the streak can only grow.  Default RobustOption keeps this on
    # the same compiled program as the transient-burst tests (the plan
    # is a dynamic operand).
    plan = make_nan_burst(s.obs.shape[0], [2], start=0, stop=10_000)
    res = flat_solve(*_args(s, f), _guarded(option), fault_plan=plan)
    assert int(res.status) == SolveStatus.FATAL_NONFINITE
    # Bailed after max_recoveries+1 consecutive failures, not max_iter.
    assert int(res.iterations) == RobustOption().max_recoveries + 1
    assert bool(res.stopped)


# ------------------------------------------- Schur-indefiniteness breakdown


def test_indefinite_fault_triggers_pcg_breakdown_and_recovery(
        problem, clean_off):
    s, option, f = problem
    plan = make_point_indefinite_burst(
        40, list(range(8)), start=2, stop=3, n_edges=s.obs.shape[0])
    res = flat_solve(*_args(s, f), _guarded(option), fault_plan=plan)
    it = int(res.iterations)
    breakdowns = np.asarray(res.trace.pcg_breakdown)[:it]
    # The guard restarted (bounded) inside the jitted PCG body, then the
    # LM guard rolled the step back and relinearised.
    assert breakdowns.sum() >= 1
    assert np.asarray(res.trace.recovery)[:it].any()
    assert int(res.status) == SolveStatus.RECOVERED
    np.testing.assert_allclose(float(res.cost), float(clean_off.cost),
                               rtol=1e-6)


def test_pcg_core_guard_is_bitwise_free_and_flags_indefinite():
    from megba_tpu.solver.pcg import _pcg_core

    rng = np.random.default_rng(0)
    a = rng.standard_normal((12, 12))
    spd = jnp.asarray(a @ a.T + 12 * np.eye(12))
    b = jnp.asarray(rng.standard_normal(12))

    def run(mat, guard, max_restarts=0):
        return _pcg_core(lambda x: mat @ x, lambda r: r, b, 50, 1e-12,
                         1e30, False, guard=guard,
                         max_restarts=max_restarts)

    x0, k0, rho0, _, re0, br0 = run(spd, False)
    x1, k1, rho1, _, re1, br1 = run(spd, True, max_restarts=2)
    assert np.array_equal(np.asarray(x0), np.asarray(x1))
    assert int(k0) == int(k1)
    assert float(rho0) == float(rho1)
    assert int(re1) == 0 and not bool(br1)

    # Indefinite operator: delta = <p, A p> flips sign -> breakdown;
    # restarts cannot cure an indefinite matrix, so the guard exits
    # flagged after the bounded budget instead of silently iterating.
    indef = jnp.asarray(a @ a.T - 30 * np.eye(12))
    _, _, _, _, re2, br2 = run(indef, True, max_restarts=2)
    assert bool(br2)
    assert int(re2) == 2


# ----------------------------------------------- preconditioner fallback


def test_schur_diag_precond_fallback_is_counted():
    from megba_tpu.solver.pcg import _schur_diag_precond, block_inv
    from megba_tpu.common import ComputeKind

    # Two cameras, one point, two edges (one per camera).  Camera 0's
    # correction overwhelms its Hpp block (huge Hll^-1) -> indefinite
    # Schur diagonal -> Cholesky NaN -> counted fallback to Hpp.
    cd, pd = 2, 2
    Hpp_d = jnp.asarray(np.stack([np.eye(cd), 4 * np.eye(cd)]),
                        jnp.float64)
    Hll_inv = jnp.asarray(
        np.tile(np.eye(pd).reshape(pd * pd, 1), (1, 1)) * 1e6, jnp.float64)
    W = jnp.asarray(
        np.stack([np.array([1.0, 0.0]), np.array([0.0, 0.0]),
                  np.array([0.0, 0.0]), np.array([0.0, 0.0])]),
        jnp.float64)  # [cd*pd, nE]: only camera 0's edge couples
    cam_idx = jnp.asarray(np.array([0, 1], np.int32))
    pt_idx = jnp.asarray(np.zeros(2, np.int32))
    minv, n_bad = _schur_diag_precond(
        Hpp_d, Hll_inv, W, None, None, cam_idx, pt_idx, 2,
        ComputeKind.EXPLICIT, None, False)
    assert int(n_bad) == 1
    # The fallen-back block IS the Hpp preconditioner; the healthy
    # block keeps the true Schur diagonal.
    np.testing.assert_allclose(np.asarray(minv)[0],
                               np.asarray(block_inv(Hpp_d))[0])
    assert np.isfinite(np.asarray(minv)).all()


def test_precond_fallback_surfaces_in_trace(problem):
    s, option, f = problem
    opt = dataclasses.replace(
        _guarded(option),
        solver_option=dataclasses.replace(
            option.solver_option,
            preconditioner=PreconditionerKind.SCHUR_DIAG))
    plan = make_point_indefinite_burst(
        40, list(range(8)), start=2, stop=3, n_edges=s.obs.shape[0])
    res = flat_solve(*_args(s, f), opt, fault_plan=plan)
    it = int(res.iterations)
    fallbacks = np.asarray(res.trace.precond_fallback)[:it]
    # The crushed Hll blocks make the Schur diagonal of the cameras
    # seeing them indefinite -> the Cholesky-NaN fallback fires and is
    # COUNTED per iteration instead of being silent.
    assert fallbacks.sum() >= 1


# ------------------------------------------------------------ semantics


def test_status_consistent_with_stop_flag(clean_off, clean_on):
    for res in (clean_off, clean_on):
        want = (SolveStatus.CONVERGED if bool(res.stopped)
                else (SolveStatus.MAX_ITER if int(res.accepted) > 0
                      else SolveStatus.STALLED))
        assert int(res.status) == want


def test_status_names():
    assert status_name(SolveStatus.RECOVERED) == "recovered"
    assert status_name(4) == "fatal_nonfinite"
    assert status_name(99) == "unknown(99)"


def test_robust_option_validation():
    base = ProblemOption()
    with pytest.raises(ValueError, match="max_recoveries"):
        validate_options(dataclasses.replace(
            base, robust_option=RobustOption(max_recoveries=0)))
    with pytest.raises(ValueError, match="damping_inflation"):
        validate_options(dataclasses.replace(
            base, robust_option=RobustOption(damping_inflation=1.0)))
    with pytest.raises(ValueError, match="pcg_max_restarts"):
        validate_options(dataclasses.replace(
            base, robust_option=RobustOption(pcg_max_restarts=-1)))


def test_fault_plan_size_mismatch_rejected(problem):
    s, option, f = problem
    plan = make_nan_burst(3, [0], start=0, stop=1)
    with pytest.raises(ValueError, match="edge_nan"):
        flat_solve(*_args(s, f), option, fault_plan=plan)


def test_lower_edge_vector_never_multiplies_nan_into_padding():
    vec = np.array([np.nan, 0.0, np.nan, 0.0])
    perm = np.array([2, 0, 1, 3, 0, 0])  # padded perm reuses real rows
    mask = np.array([1.0, 1.0, 1.0, 1.0, 0.0, 0.0])
    out = lower_edge_vector(vec, perm, mask, n_padded=8)
    assert out.shape == (8,)
    assert np.isnan(out[:2]).all()
    assert (out[3:] == 0).all()  # masked + padded slots are exact zeros


def test_with_offset_slides_window():
    plan = make_nan_burst(4, [1], start=3, stop=5)
    moved = with_offset(plan, 3)
    assert int(moved.offset) == 3
    assert isinstance(moved, FaultPlan)
    np.testing.assert_array_equal(moved.window, plan.window)


# --------------------------------------------------- chunk-resume fault


def test_resume_relinearization_fault_contained(problem, tmp_path):
    """The preemption story end to end: a transient fault hits exactly
    the resumed chunk's initial relinearisation (global iteration 3).
    Guards off, the resumed chunk's carried cost is non-finite for good;
    guards on, the solve recovers and lands on the clean chunked cost."""
    from megba_tpu.algo.checkpointed import solve_checkpointed

    s, option, f = problem
    args = (f, np.asarray(s.cameras0), np.asarray(s.points0),
            np.asarray(s.obs), np.asarray(s.cam_idx), np.asarray(s.pt_idx))
    short = dataclasses.replace(
        option,
        algo_option=dataclasses.replace(option.algo_option, max_iter=3))
    plan = make_nan_burst(s.obs.shape[0], [2, 9], start=3, stop=4)

    def two_phase(opt, name, fault=None):
        ck = str(tmp_path / f"{name}.npz")
        solve_checkpointed(
            *args, dataclasses.replace(
                short, robust_option=opt.robust_option),
            checkpoint_path=ck, checkpoint_every=3)
        kw = {} if fault is None else {"fault_plan": fault}
        return solve_checkpointed(*args, opt, checkpoint_path=ck,
                                  checkpoint_every=20, **kw)

    clean = two_phase(option, "clean")
    off = two_phase(option, "off", plan)
    assert not np.isfinite(float(off.cost))
    on = two_phase(_guarded(option), "on", plan)
    assert int(on.status) == SolveStatus.RECOVERED
    assert int(on.recoveries) >= 1
    np.testing.assert_allclose(float(on.cost), float(clean.cost),
                               rtol=1e-5)
    # The stitched trace marks the recovery at the resume point.
    rec = np.asarray(on.trace.recovery)
    assert rec[3:5].any()


def test_resume_after_fatal_stays_fatal(problem, tmp_path):
    """Fatality is sticky across a snapshot resume: the snapshot records
    the fatal bail-out, so a rerun over the same checkpoint must report
    FATAL_NONFINITE again — not re-derive recovered/converged from the
    evaluate-only resume chunk."""
    from megba_tpu.algo.checkpointed import solve_checkpointed

    s, option, f = problem
    args = (f, np.asarray(s.cameras0), np.asarray(s.points0),
            np.asarray(s.obs), np.asarray(s.cam_idx), np.asarray(s.pt_idx))
    # Persistent fault: every recovery relinearisation is poisoned too,
    # so the first chunk exhausts max_recoveries and bails fatal.
    plan = make_nan_burst(s.obs.shape[0], [2], start=0, stop=10_000)
    ck = str(tmp_path / "fatal.npz")
    first = solve_checkpointed(*args, _guarded(option), checkpoint_path=ck,
                               checkpoint_every=20, fault_plan=plan)
    assert int(first.status) == SolveStatus.FATAL_NONFINITE
    resumed = solve_checkpointed(*args, _guarded(option), checkpoint_path=ck,
                                 checkpoint_every=20, fault_plan=plan)
    assert int(resumed.status) == SolveStatus.FATAL_NONFINITE
