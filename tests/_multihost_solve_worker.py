"""Worker for the two-process sharded SOLVE test (test_multihost.py).

Run as `python tests/_multihost_solve_worker.py <process_id> <port>
<n_local_devices>`.  Both processes join one jax.distributed cluster
(2 x n_local CPU devices), build the IDENTICAL tiny synthetic BA
problem, and run ONE sharded LM solve through the real pipeline
(solve.flat_solve -> distributed_lm_solve -> shard_map over the global
mesh), with edge arrays entering via
jax.make_array_from_callback (parallel/multihost.
globalize_for_mesh).  Prints the final cost for the orchestrating test
to compare against a single-process world-2N solve — the end-to-end
parity VERDICT r04 item 6 asks for, and the capability the reference's
single-process ncclCommInitAll cannot express (handle_manager.cpp:17-22).
"""

import os
import sys

import numpy as np

# n_local virtual CPU devices per process, pinned BEFORE jax import.
_n_local = int(sys.argv[3]) if len(sys.argv) > 3 else 2
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_n_local}")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from megba_tpu.parallel.multihost import (  # noqa: E402
    enable_cpu_cross_process_collectives,
    initialize_multihost,
)


def main() -> None:
    pid, port = int(sys.argv[1]), sys.argv[2]
    # gloo CPU collectives, selected before backend init (the plain
    # XLA:CPU client refuses multiprocess computations outright).
    assert enable_cpu_cross_process_collectives(), \
        "jaxlib has no gloo CPU collectives"
    info = initialize_multihost(f"localhost:{port}", 2, pid)
    world = info["global_devices"]
    assert world == 2 * _n_local, info

    from megba_tpu.common import (  # noqa: E402
        AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal  # noqa: E402
    from megba_tpu.ops.residuals import make_residual_jacobian_fn  # noqa: E402
    from megba_tpu.solve import flat_solve  # noqa: E402

    # Deterministic problem: both processes synthesize the same bytes.
    s = make_synthetic_bal(
        num_cameras=6, num_points=90, obs_per_point=5, seed=7,
        param_noise=3e-2, pixel_noise=0.3, dtype=np.float64)
    option = ProblemOption(
        dtype=np.float64,
        world_size=world,
        compute_kind=ComputeKind.IMPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=6),
        solver_option=SolverOption(max_iter=20, tol=1e-12),
    )
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    res = flat_solve(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)
    jax.block_until_ready(res.cost)
    print(f"worker {pid} SOLVE cost {float(res.cost):.17e} "
          f"initial {float(res.initial_cost):.17e} "
          f"iters {int(res.iterations)}", flush=True)

    # Second family over the same cluster: the sharded PGO solve.
    from megba_tpu.models.pgo import (  # noqa: E402
        make_synthetic_pose_graph, solve_pgo)

    g = make_synthetic_pose_graph(num_poses=24, loop_closures=6, seed=3)
    pgo_opt = ProblemOption(
        dtype=np.float64, world_size=world,
        algo_option=AlgoOption(max_iter=5),
        solver_option=SolverOption(max_iter=15, tol=1e-12),
    )
    pres = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, pgo_opt)
    jax.block_until_ready(pres.cost)
    print(f"worker {pid} PGO cost {float(pres.cost):.17e} "
          f"initial {float(pres.initial_cost):.17e} "
          f"iters {int(pres.iterations)}", flush=True)


if __name__ == "__main__":
    main()
