"""Chunked checkpoint/resume solve driver tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.algo import lm_solve, solve_checkpointed
from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.utils.checkpoint import load_state


def setup(seed=0):
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=seed, param_noise=4e-2, pixel_noise=0.3)
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=12, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-13, refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    args = (jnp.asarray(s.cameras0), jnp.asarray(s.points0), jnp.asarray(s.obs),
            jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx))
    # lm_solve is internal (feature-major); solve_checkpointed is public
    # (edge-major) — hence the two arg tuples differ in orientation.
    lm_args = (jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
               jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx),
               jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)))
    return f, args, lm_args, option


def test_checkpointed_equals_straight_run(tmp_path):
    f, args, lm_args, option = setup()
    straight = lm_solve(f, *lm_args, option)
    ck = str(tmp_path / "run.npz")
    chunked = solve_checkpointed(f, *args, option, checkpoint_path=ck,
                                 checkpoint_every=3)
    # Chunked execution carries the exact trust-region state across chunk
    # boundaries; trajectories agree up to XLA fusion differences between
    # the in-loop and chunk-entry linearisations (~1e-10).
    # (Parameters are gauge-free; the cost is the meaningful invariant.)
    np.testing.assert_allclose(float(chunked.cost), float(straight.cost), rtol=1e-8)
    st = load_state(ck)
    assert int(st["iteration"]) >= 1 and "extra_v" in st


def test_resume_from_partial_checkpoint(tmp_path):
    f, args, lm_args, option = setup(seed=1)
    ck = str(tmp_path / "run.npz")
    # Simulate preemption: run only the first chunk.
    import dataclasses
    short = dataclasses.replace(
        option, algo_option=dataclasses.replace(option.algo_option, max_iter=4))
    solve_checkpointed(f, *args, short, checkpoint_path=ck, checkpoint_every=4)
    st1 = load_state(ck)
    assert int(st1["iteration"]) == 4
    # Resume with the full budget: picks up at iteration 4.
    resumed = solve_checkpointed(f, *args, option, checkpoint_path=ck,
                                 checkpoint_every=4)
    straight = lm_solve(f, *lm_args, option)
    np.testing.assert_allclose(float(resumed.cost), float(straight.cost), rtol=1e-10)


def test_checkpointed_aggregates_whole_run(tmp_path):
    f, args, lm_args, option = setup(seed=2)
    ck = str(tmp_path / "agg.npz")
    chunked = solve_checkpointed(f, *args, option, checkpoint_path=ck,
                                 checkpoint_every=4)
    straight = lm_solve(f, *lm_args, option)
    assert int(chunked.iterations) == int(straight.iterations)
    assert int(chunked.accepted) == int(straight.accepted)
    np.testing.assert_allclose(float(chunked.initial_cost),
                               float(straight.initial_cost), rtol=1e-10)


def test_resume_preserves_initial_cost_and_converged_state(tmp_path):
    import dataclasses
    f, args, lm_args, option = setup(seed=3)
    ck = str(tmp_path / "r.npz")
    short = dataclasses.replace(
        option, algo_option=dataclasses.replace(option.algo_option, max_iter=4))
    first = solve_checkpointed(f, *args, short, checkpoint_path=ck,
                               checkpoint_every=4)
    resumed = solve_checkpointed(f, *args, option, checkpoint_path=ck,
                                 checkpoint_every=4)
    # initial_cost must be the TRUE first cost, not the resume point's.
    np.testing.assert_allclose(float(resumed.initial_cost),
                               float(first.initial_cost), rtol=1e-10)
    # A converged checkpoint resumes without redoing LM iterations.
    done_before = int(load_state(ck)["iteration"])
    again = solve_checkpointed(f, *args, option, checkpoint_path=ck,
                               checkpoint_every=4)
    assert int(load_state(ck)["iteration"]) == done_before
    np.testing.assert_allclose(float(again.cost), float(resumed.cost), rtol=1e-10)


def test_checkpoint_every_validated(tmp_path):
    import pytest
    f, args, lm_args, option = setup()
    with pytest.raises(ValueError, match="checkpoint_every"):
        solve_checkpointed(f, *args, option,
                           checkpoint_path=str(tmp_path / "x.npz"),
                           checkpoint_every=0)


def test_multihost_helper_single_process():
    from megba_tpu.parallel import initialize_multihost
    info = initialize_multihost()
    assert info["process_count"] >= 1
    assert info["global_devices"] >= info["local_devices"] >= 1


def test_resume_rejects_foreign_checkpoint(tmp_path):
    """A snapshot from a different problem must be refused, not silently
    resumed (jnp.take would clamp mismatched indices into garbage)."""
    import pytest

    f, args, _, option = setup(seed=2)
    ck = str(tmp_path / "run.npz")
    solve_checkpointed(f, *args, option, checkpoint_path=ck,
                       checkpoint_every=4)
    # Same shapes, different topology (different seed -> different graph).
    s2 = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                            seed=3, param_noise=4e-2, pixel_noise=0.3)
    args2 = (jnp.asarray(s2.cameras0), jnp.asarray(s2.points0),
             jnp.asarray(s2.obs), jnp.asarray(s2.cam_idx),
             jnp.asarray(s2.pt_idx))
    with pytest.raises(ValueError, match="different problem"):
        solve_checkpointed(f, *args2, option, checkpoint_path=ck,
                           checkpoint_every=4)
    # Pre-guard snapshots (no fingerprint recorded) are refused too.
    st = load_state(ck)
    st.pop("extra_topology")
    np.savez(ck, **st)
    with pytest.raises(ValueError, match="different problem"):
        solve_checkpointed(f, *args, option, checkpoint_path=ck,
                           checkpoint_every=4)


def _pgo_setup(seed=0, max_iter=12):
    from megba_tpu.models.pgo import make_synthetic_pose_graph

    g = make_synthetic_pose_graph(num_poses=20, loop_closures=4,
                                  drift_noise=0.05, seed=seed)
    option = ProblemOption(
        dtype=np.float64,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-12,
                               epsilon2=1e-15),
        solver_option=SolverOption(max_iter=100, tol=1e-14,
                                   refuse_ratio=1e30))
    return g, option


def test_pgo_checkpointed_equals_straight_run(tmp_path):
    from megba_tpu.algo.checkpointed import solve_pgo_checkpointed
    from megba_tpu.models.pgo import solve_pgo

    g, option = _pgo_setup()
    straight = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)
    ck = str(tmp_path / "pgo.npz")
    chunked = solve_pgo_checkpointed(
        g.poses0, g.edge_i, g.edge_j, g.meas, option,
        checkpoint_path=ck, checkpoint_every=3)
    np.testing.assert_allclose(float(chunked.cost), float(straight.cost),
                               rtol=1e-8, atol=1e-18)
    assert int(chunked.iterations) == int(straight.iterations)
    assert int(chunked.accepted) == int(straight.accepted)
    st = load_state(ck)
    assert int(st["iteration"]) >= 1 and "extra_v" in st


def test_pgo_resume_from_partial_checkpoint(tmp_path):
    import dataclasses

    from megba_tpu.algo.checkpointed import solve_pgo_checkpointed
    from megba_tpu.models.pgo import solve_pgo

    g, option = _pgo_setup(seed=1)
    ck = str(tmp_path / "pgo_partial.npz")
    short = dataclasses.replace(
        option,
        algo_option=dataclasses.replace(option.algo_option, max_iter=4))
    solve_pgo_checkpointed(g.poses0, g.edge_i, g.edge_j, g.meas, short,
                           checkpoint_path=ck, checkpoint_every=4)
    st1 = load_state(ck)
    assert int(st1["iteration"]) == 4
    resumed = solve_pgo_checkpointed(
        g.poses0, g.edge_i, g.edge_j, g.meas, option,
        checkpoint_path=ck, checkpoint_every=4)
    straight = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)
    np.testing.assert_allclose(float(resumed.cost), float(straight.cost),
                               rtol=1e-8, atol=1e-18)

    # Foreign snapshot is refused with a topology message.
    g2, _ = _pgo_setup(seed=2)

    with pytest.raises(ValueError, match="different problem"):
        solve_pgo_checkpointed(g2.poses0, g2.edge_i, g2.edge_j, g2.meas,
                               option, checkpoint_path=ck)
