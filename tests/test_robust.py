"""Robust loss kernels: math checks + outlier-rejection end-to-end."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.algo import lm_solve
from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.ops.robust import RobustKind, rho_and_weight, robustify


def test_rho_properties():
    s = jnp.asarray([0.0, 0.5, 1.0, 4.0, 100.0])
    for kind in (RobustKind.HUBER, RobustKind.CAUCHY):
        rho, w = rho_and_weight(s, kind, delta=1.0)
        # rho(s) ~ s near zero, concave growth, weights in (0, 1].
        np.testing.assert_allclose(rho[0], 0.0, atol=1e-12)
        np.testing.assert_allclose(rho[1], s[1], rtol=0.4)
        assert np.all(np.diff(np.asarray(rho)) > 0)  # increasing
        assert np.all(np.asarray(rho) <= np.asarray(s) + 1e-12)  # below L2
        assert np.all((np.asarray(w) > 0) & (np.asarray(w) <= 1.0 + 1e-12))


def test_huber_matches_piecewise():
    delta = 2.0
    s = jnp.asarray([1.0, 4.0, 16.0])
    rho, w = rho_and_weight(s, RobustKind.HUBER, delta)
    np.testing.assert_allclose(rho[0], 1.0)  # inside: identity
    np.testing.assert_allclose(rho[2], 2 * delta * 4.0 - delta * delta)  # outside
    np.testing.assert_allclose(w[0], 1.0)
    np.testing.assert_allclose(w[2], np.sqrt(delta / 4.0))


def test_weight_derivative_consistency():
    # w^2 must equal d rho / d s (finite difference).
    for kind in (RobustKind.HUBER, RobustKind.CAUCHY):
        s = jnp.asarray([0.3, 2.0, 9.0, 50.0])
        eps = 1e-6
        rho_p, _ = rho_and_weight(s + eps, kind, 1.5)
        rho_m, _ = rho_and_weight(s - eps, kind, 1.5)
        _, w = rho_and_weight(s, kind, 1.5)
        np.testing.assert_allclose(w * w, (rho_p - rho_m) / (2 * eps),
                                   rtol=1e-4, atol=1e-7)


def test_none_kind_is_identity():
    # Feature-major rows: r [od, nE], Jc [od*cd, nE], Jp [od*pd, nE].
    r = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8)))
    Jc = jnp.asarray(np.random.default_rng(1).normal(size=(18, 8)))
    Jp = jnp.asarray(np.random.default_rng(2).normal(size=(6, 8)))
    r2, Jc2, Jp2, rho = robustify(r, Jc, Jp, RobustKind.NONE, 1.0)
    np.testing.assert_allclose(r2, r)
    np.testing.assert_allclose(rho, jnp.sum(r * r, axis=0))


def solve(s, robust_kind, delta=3.0, anchor_gauge=False):
    option = ProblemOption(
        robust_kind=robust_kind, robust_delta=delta,
        algo_option=AlgoOption(max_iter=30, epsilon1=1e-10, epsilon2=1e-13),
        solver_option=SolverOption(max_iter=120, tol=1e-13, refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    cam_fixed = None
    cameras0 = np.array(s.cameras0)
    if anchor_gauge:
        # Fix two ground-truth cameras so parameter errors measure
        # estimation quality, not gauge drift.
        cameras0[:2] = s.cameras_gt[:2]
        cam_fixed = jnp.zeros(len(cameras0), bool).at[:2].set(True)
    return lm_solve(
        f, jnp.asarray(cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(s.obs.T),
        jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)),
        option, cam_fixed=cam_fixed)


@pytest.mark.parametrize("kind", [RobustKind.HUBER, RobustKind.CAUCHY])
def test_outlier_rejection(kind):
    # Corrupt 5% of observations with gross outliers: the robust solve
    # must recover points far closer to ground truth than plain L2.
    s = make_synthetic_bal(num_cameras=8, num_points=80, obs_per_point=5,
                           seed=7, param_noise=1e-2, pixel_noise=0.2)
    rng = np.random.default_rng(0)
    n_out = max(4, len(s.obs) // 20)
    bad = rng.choice(len(s.obs), size=n_out, replace=False)
    s.obs[bad] += rng.normal(scale=300.0, size=(n_out, 2))  # gross outliers

    res_l2 = solve(s, RobustKind.NONE, anchor_gauge=True)
    res_rb = solve(s, kind, anchor_gauge=True)

    def pt_err(res):
        return float(jnp.median(jnp.linalg.norm(
            res.points - jnp.asarray(s.points_gt.T), axis=0)))

    e_l2, e_rb = pt_err(res_l2), pt_err(res_rb)
    assert e_rb < e_l2 * 0.5, (e_l2, e_rb)


def test_robust_matches_l2_on_clean_data():
    # With no outliers and a large delta the robust solve equals L2.
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=1, param_noise=2e-2, pixel_noise=0.1)
    res_l2 = solve(s, RobustKind.NONE)
    res_h = solve(s, RobustKind.HUBER, delta=1e6)
    np.testing.assert_allclose(float(res_h.cost), float(res_l2.cost), rtol=1e-8)
