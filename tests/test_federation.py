"""Federation-tier tests: artifact store, routing, stealing, rerouting.

Compile discipline (the test_serving.py contract): tier-1 keeps only
host-side machinery — frame RPC, artifact-file hardening with a
monkeypatched serializer, strict-manifest refusal, the pure
`RoutingTable` policy, and the FULL router (submit → route → steal →
worker-loss reroute → flush) driven through in-process stub workers
with zero subprocesses and zero compiles.  Everything that compiles a
real program or spawns a real worker process is marked `slow`; the
run_tests.sh federation smoke additionally kills a real worker
mid-fleet at 16-problem scale.
"""

import dataclasses
import json
import os
import socket
import threading
from concurrent.futures import Future
import time
import warnings

import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    ProblemOption,
    SolverOption,
    SolveStatus,
)
from megba_tpu.io.synthetic import make_fleet, make_synthetic_bal
from megba_tpu.serving import (
    ArtifactKey,
    ArtifactStore,
    BucketLadder,
    ColdDispatchWarning,
    CompilePool,
    FederationStats,
    FleetProblem,
    FleetResult,
    FleetRouter,
    FleetStats,
    ManifestMismatch,
    RoutingTable,
    WorkerLostError,
    classify,
    solve_many,
)
from megba_tpu.serving import artifacts as artifacts_mod
from megba_tpu.serving.federation import (
    FrameChannel,
    FrameError,
    TcpWorkerHandle,
    WorkerHandle,
    WorkerView,
    append_federation_report,
)
from megba_tpu.serving.resilience import DeadlineExceeded, EscalationPolicy
from megba_tpu.serving.transport import (
    PipeTransport,
    ReconnectPolicy,
    TcpTransport,
    heartbeat_frame,
)
from megba_tpu.serving.worker import WorkerRuntime

OPT64 = ProblemOption(dtype=np.float64,
                      algo_option=AlgoOption(max_iter=6),
                      solver_option=SolverOption(max_iter=12, tol=1e-10))
LADDER = BucketLadder()


def _mk(seed, n_pt, n_cam=4):
    s = make_synthetic_bal(num_cameras=n_cam, num_points=n_pt,
                           obs_per_point=3, seed=seed, param_noise=2e-2,
                           pixel_noise=0.3, dtype=np.float64)
    return FleetProblem.from_synthetic(s, name=f"s{seed}_p{n_pt}")


def _bits(a) -> bytes:
    return np.asarray(a).tobytes()


# ---------------------------------------------------------------------------
# Frame RPC
# ---------------------------------------------------------------------------


def _pipe_channel():
    r1, w1 = os.pipe()
    return (FrameChannel(os.fdopen(r1, "rb", buffering=0),
                         os.fdopen(w1, "wb", buffering=0)))


def test_frame_roundtrip_including_arrays():
    chan = _pipe_channel()
    msg = {"op": "solve", "x": np.arange(1000.0).reshape(10, 100),
           "nested": [1, "two", {"three": np.int32(3)}]}
    chan.send(msg)
    out = chan.recv(timeout_s=5.0)
    assert out["op"] == "solve"
    np.testing.assert_array_equal(out["x"], msg["x"])
    assert out["nested"][2]["three"] == 3
    chan.close()


def test_frame_eof_and_timeout_and_poll_abort():
    # EOF: writer closed with no bytes -> typed FrameError.
    r, w = os.pipe()
    chan = FrameChannel(os.fdopen(r, "rb", buffering=0),
                        os.fdopen(os.dup(w), "wb", buffering=0))
    os.close(w)
    chan._wfile.close()
    with pytest.raises(FrameError):
        chan.recv(timeout_s=5.0)
    # Timeout: open pipe, no frame.
    chan2 = _pipe_channel()
    with pytest.raises(TimeoutError):
        chan2.recv(timeout_s=0.15)
    # Poll abort: the liveness hook's exception propagates.

    class Boom(RuntimeError):
        pass

    def poll():
        raise Boom("dead")

    with pytest.raises(Boom):
        chan2.recv(timeout_s=5.0, poll=poll)
    chan2.close()


def test_frame_truncated_mid_frame_is_typed():
    r, w = os.pipe()
    chan = FrameChannel(os.fdopen(r, "rb", buffering=0),
                        os.fdopen(os.dup(w), "wb", buffering=0))
    import struct

    os.write(w, struct.pack(">Q", 100) + b"only-a-few-bytes")
    os.close(w)
    chan._wfile.close()
    with pytest.raises(FrameError, match="mid-frame"):
        chan.recv(timeout_s=5.0)


# ---------------------------------------------------------------------------
# Artifact store hardening (monkeypatched serializer: zero compiles)
# ---------------------------------------------------------------------------


KEY = ArtifactKey(option_fingerprint="fp", shape="c4_p16_e2048_float64",
                  lanes=2, cd=9, pd=3, od=2)


@pytest.fixture
def fake_serializer(monkeypatch):
    """Replace jax's executable (de)serializer with a byte-level fake so
    the store's file format, checksum and env checks are testable
    without compiling anything; priming is skipped the same way."""
    from jax.experimental import serialize_executable as se

    monkeypatch.setattr(se, "serialize",
                        lambda compiled: (b"XBLOB:" + compiled, None, None))
    monkeypatch.setattr(se, "deserialize_and_load",
                        lambda payload, it, ot: ("LOADED", payload))
    monkeypatch.setattr(artifacts_mod, "_PRIMED", True)
    return se


def test_artifact_roundtrip_and_digest(tmp_path, fake_serializer):
    store = ArtifactStore(str(tmp_path))
    assert store.load(KEY) is None  # plain miss: silent
    path = store.save(KEY, b"exe-bytes")
    assert os.path.basename(path) == KEY.filename()
    assert store.entries() == [KEY.filename()]
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a clean load must not warn
        loaded = store.load(KEY)
    assert loaded == ("LOADED", b"XBLOB:exe-bytes")
    d1 = store.content_digest(KEY)
    store.save(KEY, b"exe-bytes")  # re-export: byte-identical body
    assert store.content_digest(KEY) == d1


def test_artifact_corrupt_truncated_magic_schema(tmp_path, fake_serializer):
    store = ArtifactStore(str(tmp_path))
    path = store.save(KEY, b"exe")
    blob = open(path, "rb").read()

    def expect_warn(data, pattern):
        with open(path, "wb") as fh:
            fh.write(data)
        with pytest.warns(artifacts_mod.ArtifactWarning, match=pattern):
            assert store.load(KEY) is None

    expect_warn(blob[:-7], "checksum mismatch")  # truncated body
    flipped = bytearray(blob)
    flipped[-1] ^= 0xFF
    expect_warn(bytes(flipped), "checksum mismatch")  # corrupt body
    expect_warn(b"NOTMEGBA" + blob[8:], "bad magic")
    expect_warn(blob[:20], "bad magic or truncated")
    # valid container, wrong schema
    import hashlib
    import pickle

    body = pickle.dumps({"schema": "other/v9"})
    digest = hashlib.blake2b(body, digest_size=16).digest()
    expect_warn(b"MEGBAEXE" + digest + body, "unknown artifact schema")


def test_artifact_version_mismatch_names_fields(tmp_path, fake_serializer,
                                                monkeypatch):
    store = ArtifactStore(str(tmp_path))
    with monkeypatch.context() as m:
        m.setattr(
            artifacts_mod, "current_environment",
            lambda: {"jax": "0.0.1", "jaxlib": "0.0.1", "backend": "cpu"})
        store.save(KEY, b"exe")
    with pytest.warns(artifacts_mod.ArtifactWarning,
                      match=r"jaxlib='0\.0\.1'.*compile-and-refresh"):
        assert store.load(KEY) is None
    # refresh heals: a re-save under the CURRENT env loads again
    store.save(KEY, b"exe2")
    assert store.load(KEY) is not None


def test_artifact_deserialize_refusal_warns(tmp_path, fake_serializer,
                                            monkeypatch):
    from jax.experimental import serialize_executable as se

    store = ArtifactStore(str(tmp_path))
    store.save(KEY, b"exe")

    def boom(payload, it, ot):
        raise RuntimeError("Symbols not found: [...]")

    monkeypatch.setattr(se, "deserialize_and_load", boom)
    with pytest.warns(artifacts_mod.ArtifactWarning,
                      match="runtime refused"):
        assert store.load(KEY) is None


# ---------------------------------------------------------------------------
# Strict manifests
# ---------------------------------------------------------------------------


def test_manifest_strict_mismatch_names_fields(tmp_path):
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    engine = make_residual_jacobian_fn(mode=OPT64.jacobian_mode)
    manifest = tmp_path / "m.json"
    CompilePool().save_manifest(str(manifest), option=OPT64)

    # Matching option: strict is a no-op (empty manifest warms nothing).
    assert CompilePool().warm_from_manifest(
        str(manifest), engine, OPT64, strict=True) == 0

    drifted = dataclasses.replace(
        OPT64, algo_option=AlgoOption(max_iter=9))
    with pytest.raises(ManifestMismatch) as exc:
        CompilePool().warm_from_manifest(str(manifest), engine, drifted,
                                         strict=True)
    assert "algo_option.max_iter" in exc.value.fields
    assert "algo_option.max_iter" in str(exc.value)
    # non-strict: the historical warn-and-recompile contract, now
    # naming the fields too
    with pytest.warns(UserWarning, match="algo_option.max_iter"):
        CompilePool().warm_from_manifest(str(manifest), engine, drifted)

    # A telemetry-only difference is NOT a mismatch: sinks never reach
    # a program.
    sink_only = dataclasses.replace(OPT64, telemetry="/tmp/x.jsonl")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert CompilePool().warm_from_manifest(
            str(manifest), engine, sink_only, strict=True) == 0


def test_telemetry_option_shares_keys_and_artifacts(tmp_path,
                                                    fake_serializer):
    """A telemetry-carrying option must warm/export/dispatch under the
    SAME pool keys and artifact fingerprints as its stripped twin —
    sinks never reach a program, so a sink-configured replica must LOAD
    the store a sink-less exporter wrote, not silently recompile it
    (review finding: warm once keyed on the unstripped option)."""
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving.compile_pool import _sans_telemetry, pool_key
    from megba_tpu.serving.shape_class import ShapeClass

    engine = make_residual_jacobian_fn(mode=OPT64.jacobian_mode)
    with_sink = dataclasses.replace(OPT64, telemetry="/tmp/sink.jsonl")
    sc = ShapeClass(n_cam=4, n_pt=16, n_edge=2048, dtype="float64")
    assert (pool_key(engine, OPT64, sc, 1, 9, 3, 2)
            == pool_key(engine, _sans_telemetry(with_sink), sc, 1, 9, 3, 2))

    from megba_tpu.serving.compile_pool import reset_process_cache

    reset_process_cache()
    try:
        store = ArtifactStore(str(tmp_path))
        pool = CompilePool(stats=FleetStats(), artifacts=store)
        store.save(pool._artifact_key(engine, OPT64, sc, 1, 9, 3, 2,
                                      False), b"exe")
        stats = FleetStats()
        pool2 = CompilePool(stats=stats, artifacts=store)
        assert pool2.warm(engine, with_sink,
                          [{"shape": sc.to_dict(), "lanes": 1}]) == 1
        assert stats.artifact_loads == 1 and stats.artifact_compiles == 0
        # strict manifest round-trip across the sink difference
        m = tmp_path / "m.json"
        pool.save_manifest(str(m), option=with_sink)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            pool2.warm_from_manifest(str(m), engine, OPT64, strict=True)
    finally:
        reset_process_cache()


def test_manifest_without_option_config_still_refuses(tmp_path):
    """Pre-strict manifests carry only the opaque fingerprint: strict
    must still refuse, naming the placeholder."""
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    engine = make_residual_jacobian_fn(mode=OPT64.jacobian_mode)
    manifest = tmp_path / "legacy.json"
    CompilePool().save_manifest(str(manifest), option=OPT64)
    doc = json.loads(manifest.read_text())
    del doc["option_config"]
    manifest.write_text(json.dumps(doc))
    drifted = dataclasses.replace(OPT64,
                                  algo_option=AlgoOption(max_iter=9))
    with pytest.raises(ManifestMismatch) as exc:
        CompilePool().warm_from_manifest(str(manifest), engine, drifted,
                                         strict=True)
    assert any("fingerprint" in f for f in exc.value.fields)


# ---------------------------------------------------------------------------
# Routing policy (pure)
# ---------------------------------------------------------------------------


def _views(*specs):
    out = {}
    for wid, warm in specs:
        out[wid] = WorkerView(worker_id=wid, warm=set(warm))
    return out


def test_routing_warm_first_then_least_loaded_then_id():
    t = RoutingTable()
    views = _views(("w0", []), ("w1", ["B1"]), ("w2", []))
    assert t.route("B1", views) == "w1"  # warm-first
    assert t.route("B2", views) in ("w0", "w2")
    assert t.route("B2", views) == "w0"  # deterministic id tiebreak
    assert t.route("B3", views) == "w2"  # least-assigned spreads
    # sticky: B1 stays home even after w1 got loaded
    views["w1"].routed = 100
    assert t.route("B1", views) == "w1"


def test_routing_dead_home_reroutes_and_reassign():
    t = RoutingTable()
    views = _views(("w0", []), ("w1", []))
    assert t.route("B1", views) == "w0"
    views["w0"].alive = False
    orphaned = t.reassign_lost("w0", views)
    assert orphaned == ["B1"]
    assert t.route("B1", views) == "w1"
    # all dead: route returns None
    views["w1"].alive = False
    t2 = RoutingTable()
    assert t2.route("B9", views) is None


def test_steal_candidate_warm_and_deepest_only():
    t = RoutingTable()
    views = _views(("w0", ["B1", "B2"]), ("w1", ["B2"]))
    # both buckets homed on w0 (explicit: the scenario under test is
    # the steal policy, not the assignment path)
    t.assignment.update({"B1": "w0", "B2": "w0"})
    views["w0"].assigned.update({"B1", "B2"})
    depths = {"B1": 5, "B2": 9}
    # w1 is only warm on B2 -> steals B2 even though B1 is listed too
    assert t.steal_candidate("w1", views, depths) == "B2"
    # never steals its own bucket
    t.assignment["B2"] = "w1"
    assert t.steal_candidate("w1", views, depths) is None
    # never steals a bucket it would have to compile
    t.assignment["B2"] = "w0"
    views["w1"].warm.discard("B2")
    assert t.steal_candidate("w1", views, depths) is None
    # a dead victim is not a steal source (reroute handles it)
    views["w1"].warm.add("B2")
    views["w0"].alive = False
    assert t.steal_candidate("w1", views, depths) is None


# ---------------------------------------------------------------------------
# Router end-to-end through stub workers (no subprocess, no compile)
# ---------------------------------------------------------------------------


class StubWorker:
    """In-process stand-in for a worker process: same request surface,
    scripted behavior."""

    def __init__(self, worker_id, warm=(), behavior=None):
        self.worker_id = worker_id
        self.warm = set(warm)
        self.alive = True
        self.pid = 0
        self.behavior = behavior
        self.batches = []  # list of lists of problem names

    def request(self, msg, timeout_s=None):
        if msg.get("op") == "shutdown":
            return {"ok": True}
        problems = msg["problems"]
        self.batches.append([p.name for p in problems])
        if self.behavior is not None:
            return self.behavior(self, problems)
        return {"ok": True, "results": [_stub_result(p) for p in problems],
                "warm": sorted(self.warm)}

    def terminate(self):
        self.alive = False


def _stub_result(p) -> FleetResult:
    sc = classify(*p.dims(), OPT64.dtype, LADDER)
    return FleetResult(
        name=p.name, shape=sc, lane=0, lanes=1,
        cameras=np.asarray(p.cameras).copy(),
        points=np.asarray(p.points).copy(),
        cost=np.float64(1.0), initial_cost=np.float64(2.0),
        iterations=1, accepted=1, pcg_iterations=1,
        status=int(SolveStatus.CONVERGED), recoveries=0, latency_s=0.0)


def _fleet(n, n_pt=16):
    return [_mk(seed, n_pt) for seed in range(n)]


def test_router_routes_resolves_and_counts():
    probs = _fleet(4, n_pt=16) + _fleet(3, n_pt=128)
    w0, w1 = StubWorker("w0"), StubWorker("w1")
    with FleetRouter(OPT64, workers=[w0, w1], max_batch=8) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        results = [f.result(timeout=5) for f in futs]
    assert all(r.name == p.name for r, p in zip(results, probs))
    assert all(r.status == int(SolveStatus.CONVERGED) for r in results)
    d = router.stats.as_dict()
    assert d["problems"] == 7
    assert sum(d["problems_by_worker"].values()) == 7
    # two shape classes -> two homes: both workers served
    assert len(d["problems_by_worker"]) == 2
    assert d["workers_lost"] == 0 and d["reroutes"] == 0


def test_router_steal_moves_backlog_to_idle_warm_worker():
    probs = _fleet(8, n_pt=16)
    bucket = str(classify(*probs[0].dims(), OPT64.dtype, LADDER))
    release = threading.Event()

    def blocking(stub, problems):
        # First batch wedges until released: the other worker must pull
        # the backlog rather than wait behind it.
        if len(stub.batches) == 1:
            assert release.wait(timeout=30), "test deadlock"
        return {"ok": True,
                "results": [_stub_result(p) for p in problems],
                "warm": sorted(stub.warm)}

    w0 = StubWorker("w0", warm=[bucket], behavior=blocking)
    w1 = StubWorker("w1", warm=[bucket], behavior=blocking)
    try:
        with FleetRouter(OPT64, workers=[w0, w1], max_batch=4) as router:
            futs = [router.submit(p) for p in probs]
            # both workers take one 4-batch each: one owns the bucket,
            # the other STEALS the backlog it is warm for
            t0 = time.monotonic()
            while (len(w0.batches) + len(w1.batches) < 2
                   and time.monotonic() - t0 < 10):
                time.sleep(0.005)
            release.set()
            router.flush()
            results = [f.result(timeout=10) for f in futs]
    finally:
        release.set()
    assert len(results) == 8
    d = router.stats.as_dict()
    assert d["steals"] == 1, d
    assert d["stolen_problems"] == 4, d
    assert sorted(d["problems_by_worker"].values()) == [4, 4], d


def test_router_worker_loss_reroutes_to_survivor():
    probs = _fleet(6, n_pt=16)

    def dying(stub, problems):
        raise WorkerLostError(stub.worker_id, "stub SIGKILL")

    w0 = StubWorker("w0", behavior=dying)  # id tiebreak homes bucket here
    w1 = StubWorker("w1")  # not warm: cannot steal, only reroute-absorb
    with FleetRouter(OPT64, workers=[w0, w1], max_batch=16,
                     steal=False) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        results = [f.result(timeout=10) for f in futs]
        assert len(results) == 6
        d = router.stats.as_dict()
        assert d["workers_lost"] == 1 and d["lost_workers"] == ["w0"]
        assert d["reroutes"] == 6
        assert d["problems_by_worker"] == {"w1": 6}
        # the router keeps serving on the survivor
        fut = router.submit(_mk(99, 16))
        router.flush()
        assert fut.result(timeout=10).name == "s99_p16"


def test_router_all_workers_lost_fails_typed_and_flush_returns():
    def dying(stub, problems):
        raise WorkerLostError(stub.worker_id, "stub death")

    probs = _fleet(5, n_pt=16)
    w0 = StubWorker("w0", behavior=dying)
    w1 = StubWorker("w1", behavior=dying)
    router = FleetRouter(OPT64, workers=[w0, w1], max_batch=4)
    futs = [router.submit(p) for p in probs]
    router.flush()  # must NOT wedge
    for f in futs:
        with pytest.raises(WorkerLostError, match="no surviving workers"):
            f.result(timeout=5)
    with pytest.raises(WorkerLostError, match="no surviving workers"):
        router.submit(_mk(7, 16))
    router.close()
    assert router.stats.as_dict()["workers_lost"] == 2


def test_router_max_reroutes_exhausted_is_typed():
    calls = []

    def dying(stub, problems):
        calls.append(stub.worker_id)
        raise WorkerLostError(stub.worker_id, "stub death")

    probs = _fleet(2, n_pt=16)
    # three workers, max_reroutes=1: initial + 1 reroute both die, the
    # THIRD worker never gets the problems (bounded retry, PR 8 stance)
    w = [StubWorker(f"w{i}", behavior=dying) for i in range(3)]
    with FleetRouter(OPT64, workers=w, max_batch=4,
                     max_reroutes=1, steal=False) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        for f in futs:
            with pytest.raises(WorkerLostError, match="rerouted 1 times"):
                f.result(timeout=5)
    d = router.stats.as_dict()
    assert d["workers_lost"] == 2  # the third never dispatched
    assert d["reroute_failures"] == 2
    assert len(set(calls)) == 2


def test_router_solve_error_fails_batch_but_worker_survives():
    def flaky(stub, problems):
        if len(stub.batches) == 1:
            return {"ok": False, "error": "ValueError('bad operand')"}
        return {"ok": True,
                "results": [_stub_result(p) for p in problems],
                "warm": sorted(stub.warm)}

    w0 = StubWorker("w0", behavior=flaky)
    with FleetRouter(OPT64, workers=[w0], max_batch=4) as router:
        bad = router.submit(_mk(0, 16))
        router.flush()
        with pytest.raises(RuntimeError, match="bad operand"):
            bad.result(timeout=5)
        good = router.submit(_mk(1, 16))
        router.flush()
        assert good.result(timeout=5).name == "s1_p16"
    assert router.stats.as_dict()["workers_lost"] == 0


def test_router_deadline_shed_before_dispatch():
    gate = threading.Event()

    def slow(stub, problems):
        gate.wait(timeout=30)
        return {"ok": True,
                "results": [_stub_result(p) for p in problems],
                "warm": sorted(stub.warm)}

    w0 = StubWorker("w0", behavior=slow)
    try:
        with FleetRouter(OPT64, workers=[w0], max_batch=1) as router:
            first = router.submit(_mk(0, 16))  # occupies the worker
            doomed = router.submit(_mk(1, 16), deadline_s=0.01)
            time.sleep(0.05)
            gate.set()
            router.flush()
            assert first.result(timeout=10) is not None
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=5)
    finally:
        gate.set()
    assert router.stats.as_dict()["sheds"] == 1


def test_router_late_completion_flagged_deadline_missed():
    """The FleetQueue parity contract: a result completing AFTER its
    deadline is DELIVERED, flagged and counted — not silently on time
    and not shed (it was dispatched in time)."""
    def slow(stub, problems):
        time.sleep(0.15)
        return {"ok": True,
                "results": [_stub_result(p) for p in problems],
                "warm": sorted(stub.warm)}

    w0 = StubWorker("w0", behavior=slow)
    with FleetRouter(OPT64, workers=[w0], max_batch=4) as router:
        fut = router.submit(_mk(0, 16), deadline_s=0.05)
        router.flush()
        r = fut.result(timeout=5)
    assert r.deadline_missed is True
    d = router.stats.as_dict()
    assert d["deadline_misses"] == 1 and d["sheds"] == 0, d


def test_router_close_idempotent_single_telemetry_line(tmp_path):
    sink = str(tmp_path / "fed.jsonl")
    router = FleetRouter(OPT64, workers=[StubWorker("w0")],
                         telemetry=sink)
    fut = router.submit(_mk(0, 16))
    router.flush()
    assert fut.result(timeout=5) is not None
    router.close()
    router.close()  # explicit double close
    with open(sink) as fh:
        lines = [l for l in fh if l.strip()]
    assert len(lines) == 1, "duplicate federation report on double close"


def test_router_done_callback_may_reenter_router():
    """Shed and worker-lost resolutions run OUTSIDE the router lock: a
    done-callback that re-enters the router (submit from a completion
    hook) must not self-deadlock the serve thread."""
    resubmitted = []

    def dying(stub, problems):
        raise WorkerLostError(stub.worker_id, "stub death")

    w0 = StubWorker("w0", behavior=dying)
    w1 = StubWorker("w1")
    router = FleetRouter(OPT64, workers=[w0, w1], max_batch=4,
                         steal=False, max_reroutes=0)
    fut = router.submit(_mk(0, 16))

    def reenter(f):
        try:
            resubmitted.append(router.submit(_mk(1, 16)))
        except Exception as e:  # noqa: BLE001 - recorded for the assert
            resubmitted.append(e)

    fut.add_done_callback(reenter)
    router.flush()
    with pytest.raises(WorkerLostError):
        fut.result(timeout=5)
    assert len(resubmitted) == 1
    if isinstance(resubmitted[0], Exception):
        raise AssertionError(f"re-entrant submit failed: {resubmitted[0]}")
    router.flush()
    assert resubmitted[0].result(timeout=5) is not None
    router.close()


def test_router_validation():
    with pytest.raises(ValueError, match="n_workers"):
        FleetRouter(OPT64, n_workers=0)
    with pytest.raises(ValueError, match="max_reroutes"):
        FleetRouter(OPT64, workers=[StubWorker("w0")], max_reroutes=-1)
    router = FleetRouter(OPT64, workers=[StubWorker("w0")])
    with pytest.raises(ValueError, match="deadline_s"):
        router.submit(_mk(0, 16), deadline_s=-1.0)
    router.close()
    with pytest.raises(RuntimeError, match="closed"):
        router.submit(_mk(0, 16))


# ---------------------------------------------------------------------------
# Federation observability
# ---------------------------------------------------------------------------


def test_federation_stats_counters_and_report():
    st = FederationStats()
    st.record_batch("w0", 4, stolen=False)
    st.record_batch("w1", 2, stolen=True)
    st.record_reroute(3)
    st.record_worker_lost("w1")
    st.record_cold_start("w0", {"mode": "artifact", "warm_s": 0.42,
                                "artifact_loads": 5,
                                "artifact_compiles": 0})
    st.record_first_solve("w0", {"traces": 0, "wall_s": 0.5})
    d = st.as_dict()
    assert d["problems"] == 6 and d["steals"] == 1
    assert d["stolen_problems"] == 2 and d["reroutes"] == 3
    assert d["workers_lost"] == 1 and d["lost_workers"] == ["w1"]
    text = st.report()
    assert "6 problems" in text and "1 steals" in text
    assert "artifact 0.420s" in text and "first solve 0 traces" in text


def test_summarize_federation_block(tmp_path):
    from megba_tpu.observability import summarize
    from megba_tpu.utils.timing import PhaseTimer

    st = FederationStats()
    st.record_batch("w0", 9, stolen=False)
    st.record_batch("w1", 7, stolen=True)
    st.record_reroute(5)
    st.record_worker_lost("w1")
    st.record_cold_start("w0", {"mode": "artifact", "warm_s": 0.351,
                                "artifact_loads": 5,
                                "artifact_compiles": 0})
    st.record_cold_start("w1", {"mode": "compile", "warm_s": 93.2,
                                "artifact_loads": 0,
                                "artifact_compiles": 5})
    st.record_first_solve("w0", {"traces": 0, "wall_s": 1.0})
    sink = str(tmp_path / "fed.jsonl")
    append_federation_report(OPT64, st, PhaseTimer(), sink)
    # a second (older-router) snapshot must SUM, not duplicate: same
    # router id keeps only the newest line
    append_federation_report(OPT64, st, PhaseTimer(), sink)
    out = summarize.aggregate_paths([sink])
    assert "federation: 16 problems across 2 workers" in out
    assert "w0:9 / w1:7" in out
    assert "1 steals (7 problems)" in out
    assert "5 rerouted, 1 workers lost" in out
    assert "cold start w0: artifact 0.351s (5 loaded / 0 compiled)" in out
    assert "first solve 0 traces" in out
    assert "cold start w1: compile 93.200s (0 loaded / 5 compiled)" in out
    assert summarize.main(["--aggregate", sink]) == 0


def test_solve_report_federation_round_trip():
    from megba_tpu.observability.report import SolveReport

    rep = SolveReport(problem={}, config={}, backend={}, phases={},
                      result={}, federation={"router": "abc",
                                             "problems": 3})
    back = SolveReport.from_json(rep.to_json())
    assert back.federation == {"router": "abc", "problems": 3}
    # pre-federation lines (no field) still parse
    line = json.dumps({"problem": {}, "config": {}, "backend": {},
                       "phases": {}, "result": {}})
    assert SolveReport.from_json(line).federation is None


def test_fleet_stats_artifact_counters():
    st = FleetStats()
    st.record_artifact(True)
    st.record_artifact(True)
    st.record_artifact(False)
    d = st.as_dict()
    assert d["artifact_loads"] == 2 and d["artifact_compiles"] == 1
    assert "artifact store: 2 loaded / 1 compiled" in st.report()


# ---------------------------------------------------------------------------
# Transport supervision (fail-fast, escalation, cold dispatch, reconnect)
# ---------------------------------------------------------------------------


def test_worker_handle_fails_fast_from_recorded_death():
    """Once ONE waiter observes the death, every later request must
    fail from the recorded reason immediately — never re-spend a
    watchdog budget on a channel known dead."""
    r1, w1 = os.pipe()  # router -> worker (never read; stays open)
    r2, w2 = os.pipe()  # worker -> router
    chan = FrameChannel(os.fdopen(r2, "rb", buffering=0),
                        os.fdopen(w1, "wb", buffering=0))
    h = WorkerHandle("w0", None, chan, log_path="/nonexistent")
    os.close(w2)  # worker side gone: the reply read sees EOF
    with pytest.raises(WorkerLostError):
        h.request({"op": "stats"}, timeout_s=5.0)
    t0 = time.monotonic()
    with pytest.raises(WorkerLostError, match="fail-fast"):
        h.request({"op": "stats"}, timeout_s=30.0)
    # Well under the 30s watchdog budget it would otherwise burn.
    assert time.monotonic() - t0 < 1.0
    chan.close()
    os.close(r1)


def test_router_escalation_retries_once_then_succeeds():
    """Past max_reroutes the router consults the EscalationPolicy
    ladder ONCE: the item requeues behind the policy's backoff and a
    survivor serves it instead of failing typed."""
    def dying(stub, problems):
        raise WorkerLostError(stub.worker_id, "stub death")

    w0 = StubWorker("w0", behavior=dying)
    w1 = StubWorker("w1")
    esc = EscalationPolicy(backoff_base_s=0.01)
    with FleetRouter(OPT64, workers=[w0, w1], max_batch=4, steal=False,
                     max_reroutes=0, escalation=esc) as router:
        fut = router.submit(_mk(0, 16))
        router.flush()
        assert fut.result(timeout=10).name == "s0_p16"
    d = router.stats.as_dict()
    assert d["escalations"] == 1, d
    assert d["workers_lost"] == 1 and d["reroute_failures"] == 0, d


def test_router_escalation_consumed_fails_typed():
    """The ladder is consulted once per item: a second loss after the
    escalated retry fails typed, naming the consumed escalation."""
    def dying(stub, problems):
        raise WorkerLostError(stub.worker_id, "stub death")

    workers = [StubWorker(f"w{i}", behavior=dying) for i in range(3)]
    esc = EscalationPolicy(backoff_base_s=0.01)
    with FleetRouter(OPT64, workers=workers, max_batch=4, steal=False,
                     max_reroutes=0, escalation=esc) as router:
        fut = router.submit(_mk(0, 16))
        router.flush()
        with pytest.raises(WorkerLostError, match="escalation consumed"):
            fut.result(timeout=10)
    assert router.stats.as_dict()["escalations"] == 1


def test_router_cold_dispatch_counted_and_warned_once():
    """A dispatch with no warm program on the target counts EVERY
    time but warns ONCE per (bucket, lanes, rung) key."""
    w0 = StubWorker("w0")  # never reports anything warm
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        # max_batch=1 pins every dispatch to lanes=1: one warn key.
        with FleetRouter(OPT64, workers=[w0], max_batch=1) as router:
            for wave in range(2):  # same key twice
                futs = [router.submit(_mk(2 * wave + i, 16))
                        for i in range(2)]
                router.flush()
                for f in futs:
                    assert f.result(timeout=10) is not None
    cold = [w for w in rec if issubclass(w.category, ColdDispatchWarning)]
    assert len(cold) == 1, [str(w.message) for w in rec]
    msg = str(cold[0].message)
    assert "lanes=1" in msg and "rung=0" in msg and "w0" in msg
    assert router.stats.as_dict()["cold_dispatches"] == 4


def test_tcp_handle_reconnect_resends_same_seq():
    """The supervision contract end to end, no sockets faked: a
    scripted server receives the request and DROPS the connection;
    adopting a fresh transport makes the stranded reader resend the
    SAME sequence id and resolve from the second server's reply."""
    events = []

    def on_event(event, worker="?", **kw):
        events.append(event)

    a1, b1 = socket.socketpair()
    h = TcpWorkerHandle(
        "w0", TcpTransport(a1),
        reconnect=ReconnectPolicy(window_s=10.0, base_s=0.01),
        conn_dead_after_s=60.0, on_event=on_event)
    srv1 = TcpTransport(b1)
    got = {}

    def server1():
        got["req1"] = srv1.recv(timeout_s=10.0)
        srv1.close()  # drop mid-request, no reply

    a2, b2 = socket.socketpair()
    srv2 = TcpTransport(b2)

    def server2():
        req = srv2.recv(timeout_s=10.0)
        got["req2"] = req
        srv2.send(heartbeat_frame(1, "w0"))  # skimmed by the reader
        srv2.send({"ok": True, "seq": req["seq"], "answer": 42})

    result = {}

    def do_request():
        result["reply"] = h.request({"op": "stats"}, timeout_s=30.0)

    t1 = threading.Thread(target=server1)
    t1.start()
    rt = threading.Thread(target=do_request)
    rt.start()
    t1.join(timeout=10.0)
    t2 = threading.Thread(target=server2)
    t2.start()
    h.adopt(TcpTransport(a2), incarnation=1)
    rt.join(timeout=10.0)
    t2.join(timeout=10.0)
    assert not rt.is_alive()
    assert result["reply"]["answer"] == 42
    assert got["req1"]["seq"] == got["req2"]["seq"] == result["reply"]["seq"]
    assert "conn_lost" in events and "resend" in events
    # Epoch 1 is the first registration: a connect, not a recovery.
    assert "connect" in events and "reconnect" not in events
    h.terminate()
    srv2.close()


def test_tcp_handle_idle_gap_is_not_connection_loss():
    """last_rx is only refreshed while a reader is listening, so an
    IDLE handle's heartbeats pile up unread and the clock goes stale.
    A request after an idle gap longer than conn_dead_after_s must not
    read that gap as silence: the staleness window starts when the
    reader starts listening (regression: the false conn_lost stranded
    the reader in a reconnect window no healthy worker ever ends)."""
    events = []
    a, b = socket.socketpair()
    h = TcpWorkerHandle(
        "w0", TcpTransport(a), conn_dead_after_s=0.3,
        on_event=lambda event, **kw: events.append(event))
    srv = TcpTransport(b)

    def server():
        req = srv.recv(timeout_s=10.0)
        srv.send({"ok": True, "seq": req["seq"], "answer": 7})

    t = threading.Thread(target=server)
    t.start()
    time.sleep(0.8)  # idle for >2x the staleness threshold
    reply = h.request({"op": "stats"}, timeout_s=10.0)
    t.join(timeout=10.0)
    assert reply["answer"] == 7
    assert "conn_lost" not in events
    h.terminate()
    srv.close()


def test_worker_runtime_dedup_serves_cached_reply(monkeypatch):
    """A resend with an already-answered seq is served from the reply
    cache — counted, never re-executed."""
    # The runtime tags the process env; record-and-restore via
    # monkeypatch so later batcher tests see their own tag.
    monkeypatch.setenv("MEGBA_FEDERATION_WORKER", "test-orig")
    runtime = WorkerRuntime("wdedup", {"option": OPT64})
    r1, w1 = os.pipe()  # router -> worker
    r2, w2 = os.pipe()  # worker -> router
    worker_chan = PipeTransport(os.fdopen(r1, "rb", buffering=0),
                                os.fdopen(w2, "wb", buffering=0))
    router_chan = PipeTransport(os.fdopen(r2, "rb", buffering=0),
                                os.fdopen(w1, "wb", buffering=0))
    t = threading.Thread(target=runtime.serve, args=(worker_chan,))
    t.start()
    try:
        router_chan.send({"op": "stats", "seq": 7})
        first = router_chan.recv(timeout_s=10.0)
        router_chan.send({"op": "stats", "seq": 7})  # resend, same seq
        second = router_chan.recv(timeout_s=10.0)
        assert first["seq"] == second["seq"] == 7
        assert second == first  # the cached reply, bit for bit
        assert runtime.dedup.hit_count() == 1
        assert runtime.timer.counts.get("transport_dedup_hit") == 1
        router_chan.send({"op": "shutdown", "seq": 8})
        assert router_chan.recv(timeout_s=10.0)["ok"]
    finally:
        t.join(timeout=10.0)
        router_chan.close()
        worker_chan.close()
    assert not t.is_alive()


# ---------------------------------------------------------------------------
# Real programs (slow lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_artifact_export_import_bitwise_and_zero_trace(tmp_path):
    """The cold-start contract on a REAL bucket program: export →
    fresh-replica state → warm from artifacts (zero compiles, zero
    traces) → dispatch bitwise-identical to the exporter's."""
    from megba_tpu.analysis import retrace
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving.compile_pool import reset_process_cache

    engine = make_residual_jacobian_fn(mode=OPT64.jacobian_mode)
    store = ArtifactStore(str(tmp_path / "store"))
    probs = [_mk(0, 16), _mk(1, 16)]

    stats = FleetStats()
    pool = CompilePool(stats=stats, artifacts=store)
    base = solve_many(probs, OPT64, pool=pool, stats=stats)
    manifest = str(tmp_path / "manifest.json")
    pool.save_manifest(manifest, option=OPT64)
    assert pool.export_artifacts(engine, OPT64) == 1
    assert len(store.entries()) == 1

    # -- fresh replica ---------------------------------------------------
    reset_process_cache()
    stats2 = FleetStats()
    pool2 = CompilePool(stats=stats2, artifacts=store)
    snap = retrace.snapshot()
    assert pool2.warm_from_manifest(manifest, engine, OPT64,
                                    strict=True) == 1
    assert stats2.artifact_loads == 1 and stats2.artifact_compiles == 0
    again = solve_many(probs, OPT64, pool=pool2, stats=stats2)
    new = {k: v - snap.get(k, 0) for k, v in retrace.snapshot().items()
           if v > snap.get(k, 0)}
    assert sum(new.values()) == 0, (
        f"artifact-warmed replica traced a program: {new}")
    assert stats2.pool_hits >= 1 and stats2.pool_misses == 0
    for a, b in zip(base, again):
        assert _bits(a.cameras) == _bits(b.cameras)
        assert _bits(a.points) == _bits(b.points)
        assert _bits(a.cost) == _bits(b.cost)
        assert int(a.status) == int(b.status)


@pytest.mark.slow
def test_router_two_real_workers_bitwise_vs_solve_many(tmp_path):
    """Two REAL worker processes warmed from artifacts: zero first-solve
    traces in both, results bitwise vs a single-host solve_many
    control.  (The kill/reroute path at scale lives in the run_tests.sh
    federation smoke.)"""
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving.compile_pool import reset_process_cache

    engine = make_residual_jacobian_fn(mode=OPT64.jacobian_mode)
    store_root = str(tmp_path / "store")
    store = ArtifactStore(store_root)
    probs = [_mk(i, 16) for i in range(3)] + [_mk(i, 128) for i in range(2)]

    stats = FleetStats()
    pool = CompilePool(stats=stats, artifacts=store)
    control = solve_many(probs, OPT64, pool=pool, stats=stats)
    manifest = str(tmp_path / "manifest.json")
    pool.save_manifest(manifest, option=OPT64)
    assert pool.export_artifacts(engine, OPT64) == len(store.entries())

    with FleetRouter(OPT64, n_workers=2, artifacts=store_root,
                     manifest=manifest, strict_manifest=True) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        results = [f.result(timeout=60) for f in futs]
        d = router.stats.as_dict()
    for r, c in zip(results, control):
        assert _bits(r.cameras) == _bits(c.cameras), r.name
        assert _bits(r.cost) == _bits(c.cost), r.name
        assert int(r.status) == int(c.status), r.name
    for wid, cs in d["cold_start"].items():
        assert cs["mode"] == "artifact", (wid, cs)
        assert cs["artifact_compiles"] == 0, (wid, cs)
    for wid, fs in d["first_solve"].items():
        assert fs["traces"] == 0, (wid, fs)
    assert sum(d["problems_by_worker"].values()) == len(probs)
